"""Ensemble failover tests: N members, one replicated tree, one session table.

Production points registrar at a 3–5 member ZooKeeper ensemble (reference
etc/config.coal.json:9-16 lists one host per member; README's ops notes
describe member maintenance).  The correctness property that matters for
DNS availability: when the member a registrar is connected to dies, the
client reattaches its *same* session to another member and the ephemeral
znodes — the DNS records — never disappear.  Round 1 only tested failover
against a single restarted server; these tests exercise a real multi-member
topology via ZKEnsemble.
"""

import asyncio
import json
import os
import socket
import subprocess
import sys

import pytest

from registrar_tpu.registration import register
from registrar_tpu.testing.server import ZKEnsemble, ZKServer
from registrar_tpu.zk.client import Op, ZKClient
from registrar_tpu.zk.protocol import EventType
from registrar_tpu.zk.protocol import CreateFlag, ZKError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def member_holding(ens, session_id):
    """Index of the live member carrying ``session_id``'s connection."""
    for i, member in enumerate(ens.servers):
        if member is None or member._server is None:
            continue
        for conn in member._conns:
            if conn.session is not None and conn.session.session_id == session_id:
                return i
    raise AssertionError(f"no member holds session 0x{session_id:x}")


async def test_replication_visible_through_every_member():
    async with ZKEnsemble(3) as ens:
        writer = await ZKClient([ens.addresses[0]]).connect()
        try:
            await writer.create("/shared", b"payload")
            # Readers pinned to each *other* member see the write.
            for addr in ens.addresses[1:]:
                reader = await ZKClient([addr]).connect()
                try:
                    data, _ = await reader.get("/shared")
                    assert data == b"payload"
                finally:
                    await reader.close()
        finally:
            await writer.close()


async def test_watch_set_via_one_member_fires_on_write_via_another():
    async with ZKEnsemble(2) as ens:
        watcher = await ZKClient([ens.addresses[0]]).connect()
        writer = await ZKClient([ens.addresses[1]]).connect()
        try:
            await watcher.create("/w", b"a")
            fired = asyncio.Event()
            events = []

            def on_event(ev):
                events.append(ev)
                fired.set()

            watcher.watch("/w", on_event)
            await watcher.get("/w", watch=True)
            await writer.set_data("/w", b"b")
            await asyncio.wait_for(fired.wait(), timeout=5)
            assert events and events[0].path == "/w"
        finally:
            await watcher.close()
            await writer.close()


async def test_failover_reattaches_session_with_ephemerals_intact():
    async with ZKEnsemble(3) as ens:
        client = await ZKClient(ens.addresses, timeout_ms=60_000).connect()
        try:
            await client.create("/eph", b"x", CreateFlag.EPHEMERAL)
            sid = client.session_id
            victim = member_holding(ens, sid)

            reconnected = asyncio.Event()
            client.on("connect", lambda *a: reconnected.set())
            await ens.kill(victim)

            # The DNS-visibility property: at no point during failover is
            # the ephemeral gone from the replicated tree.
            deadline = asyncio.get_event_loop().time() + 10
            while not reconnected.is_set():
                node = ens.get_node("/eph")
                assert node is not None and node.ephemeral_owner == sid, (
                    "ephemeral vanished during failover"
                )
                if asyncio.get_event_loop().time() > deadline:
                    raise AssertionError("client never reattached")
                await asyncio.sleep(0.01)

            assert client.session_id == sid  # same session, not a new one
            new_home = member_holding(ens, sid)
            assert new_home != victim
            st = await client.stat("/eph")
            assert st.ephemeral_owner == sid
        finally:
            await client.close()


async def test_registration_survives_member_death_without_reregistering():
    # The VERDICT acceptance case: kill the connected member mid-run; the
    # registration must survive with no re-registration (same czxid, same
    # ephemeral owner) and no DNS-visible gap.
    async with ZKEnsemble(3) as ens:
        client = await ZKClient(ens.addresses, timeout_ms=60_000).connect()
        try:
            znodes = await register(
                zk=client,
                registration={"domain": "svc.test.us", "type": "load_balancer"},
                admin_ip="10.0.0.5",
                hostname="host-a",
                settle_delay=0,
            )
            host_node = [p for p in znodes if p.endswith("/host-a")][0]
            before = ens.get_node(host_node)
            assert before is not None
            czxid_before = before.czxid
            sid = client.session_id

            victim = member_holding(ens, sid)
            reconnected = asyncio.Event()
            client.on("connect", lambda *a: reconnected.set())
            await ens.kill(victim)
            await asyncio.wait_for(reconnected.wait(), timeout=10)

            # Heartbeat (the agent's liveness probe) succeeds post-failover.
            await client.heartbeat(znodes)

            after = ens.get_node(host_node)
            assert after is not None
            assert after.ephemeral_owner == sid
            # Same czxid == the node was never deleted + recreated, i.e.
            # the pipeline did not re-run.
            assert after.czxid == czxid_before
        finally:
            await client.close()


async def test_session_expires_while_home_member_is_down():
    # If the client does NOT come back, the surviving QUORUM's leader
    # must still reap the session and its ephemerals (exactly real ZK:
    # the session tracker lives on the leader — a 3-member ensemble
    # losing one member keeps a leader; see TestQuorum for the
    # quorum-lost case where sessions freeze instead).
    async with ZKEnsemble(3, tick_ms=20) as ens:
        client = await ZKClient(
            ens.addresses, timeout_ms=200, reconnect=False
        ).connect()
        await client.create("/gone", b"", CreateFlag.EPHEMERAL)
        sid = client.session_id
        victim = member_holding(ens, sid)
        await ens.kill(victim)
        await client.close()  # client gives up instead of failing over
        await asyncio.sleep(0.6)  # > negotiated session timeout
        assert ens.get_node("/gone") is None
        assert sid not in ens.state.sessions


async def test_member_restart_rejoins_with_shared_state():
    async with ZKEnsemble(3) as ens:
        client = await ZKClient([ens.addresses[0]]).connect()
        try:
            await client.create("/persist", b"v1")
            await ens.kill(2)
            await client.set_data("/persist", b"v2")  # write while 2 is down
            member = await ens.restart(2)
            direct = await ZKClient([(member.host, member.port)]).connect()
            try:
                data, _ = await direct.get("/persist")
                assert data == b"v2"  # rejoined member serves the write
            finally:
                await direct.close()
        finally:
            await client.close()


async def test_leader_label_moves_on_leader_death():
    async with ZKEnsemble(3) as ens:
        modes = [m.mode for m in ens.live]
        assert modes == ["leader", "follower", "follower"]
        await ens.kill(0)
        modes = [m.mode for m in ens.live]
        assert modes == ["leader", "follower"]


async def test_ensemble_size_one_behaves_like_standalone():
    async with ZKEnsemble(1) as ens:
        client = await ZKClient(ens.addresses).connect()
        try:
            await client.create("/solo", b"ok")
            assert ens.get_node("/solo").data == b"ok"
        finally:
            await client.close()


async def test_daemon_rides_through_member_death(tmp_path):
    # Full-stack version of the failover property: the real daemon
    # process, configured with the whole ensemble's servers list, keeps
    # its registration (and never re-registers or restarts) when the
    # member it is connected to dies.
    async with ZKEnsemble(3, max_session_timeout_ms=60_000) as ens:
        config = {
            "registration": {
                "domain": "ha.e2e.registrar",
                "type": "host",
                "heartbeatInterval": 200,
            },
            "adminIp": "10.66.66.70",
            "zookeeper": {
                "servers": [
                    {"host": h, "port": p} for h, p in ens.addresses
                ],
                "timeout": 30_000,
            },
        }
        cfg_path = tmp_path / "config.json"
        cfg_path.write_text(json.dumps(config))
        proc = subprocess.Popen(
            [sys.executable, "-m", "registrar_tpu", "-f", str(cfg_path)],
            cwd=REPO,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            env={**os.environ, "PYTHONPATH": REPO},
        )
        try:
            node = f"/registrar/e2e/ha/{socket.gethostname()}"
            deadline = asyncio.get_event_loop().time() + 15
            while ens.get_node(node) is None:
                assert asyncio.get_event_loop().time() < deadline
                await asyncio.sleep(0.1)
            before = ens.get_node(node)
            sid = before.ephemeral_owner
            czxid = before.czxid

            victim = member_holding(ens, sid)
            await ens.kill(victim)

            # Wait until the daemon's session lands on a surviving member.
            deadline = asyncio.get_event_loop().time() + 15
            while True:
                # The znode must exist at every instant of the failover.
                now = ens.get_node(node)
                assert now is not None and now.ephemeral_owner == sid
                try:
                    if member_holding(ens, sid) != victim:
                        break
                except AssertionError:
                    pass  # between connections
                assert asyncio.get_event_loop().time() < deadline
                await asyncio.sleep(0.05)

            # Give a few heartbeat intervals to shake out re-registration.
            await asyncio.sleep(1.0)
            after = ens.get_node(node)
            assert after.ephemeral_owner == sid
            assert after.czxid == czxid  # never deleted + recreated
            assert proc.poll() is None  # daemon never crashed/restarted
        finally:
            if proc.poll() is None:
                proc.terminate()
            # communicate(), not wait(): a wedged daemon spewing into the
            # pipe would fill the OS buffer and deadlock a bare wait().
            out_b, _ = proc.communicate(timeout=15)
            out = out_b.decode()
        registered_events = [
            line for line in out.splitlines() if "registrar: registered" in line
        ]
        assert len(registered_events) == 1, out  # exactly one registration


class TestReplicationLag:
    """A member with apply_delay_ms set serves stale reads until sync()
    — the scenario ZKClient.sync's docstring promises to fence (round-3
    verdict: with lag-free shared state, sync was an untestable no-op)."""

    async def test_stale_reads_until_sync_forces_catch_up(self):
        async with ZKEnsemble(2) as ens:
            writer = await ZKClient([ens.addresses[0]]).connect()
            reader = await ZKClient([ens.addresses[1]]).connect()
            try:
                await writer.create("/lag", b"old")
                await writer.create("/lag/a", b"")
                # Member 1 starts lagging from the next commit on; its
                # delay is far beyond the test so only sync() catches up.
                ens.set_lag(1, 60_000)
                await writer.put("/lag", b"new")
                await writer.create("/lag/b", b"")

                # Stale data, stale children, stale stat via member 1 …
                data, stat = await reader.get("/lag")
                assert data == b"old"
                assert stat.version == 0
                assert await reader.get_children("/lag") == ["a"]
                # … while member 0 is current.
                assert (await writer.get("/lag"))[0] == b"new"

                # sync() through the lagging member is the read barrier.
                await reader.sync("/lag")
                data, stat = await reader.get("/lag")
                assert data == b"new"
                assert stat.version == 1
                assert await reader.get_children("/lag") == ["a", "b"]
            finally:
                await reader.close()
                await writer.close()

    async def test_stale_exists_and_deleted_node_still_visible(self):
        async with ZKEnsemble(2) as ens:
            writer = await ZKClient([ens.addresses[0]]).connect()
            reader = await ZKClient([ens.addresses[1]]).connect()
            try:
                await writer.create("/ghost", b"x")
                ens.set_lag(1, 60_000)
                await writer.unlink("/ghost")
                # The lagging member still shows the deleted node …
                assert await reader.exists("/ghost") is not None
                assert await writer.exists("/ghost") is None
                # … until the barrier.
                await reader.sync("/")
                assert await reader.exists("/ghost") is None
            finally:
                await reader.close()
                await writer.close()

    async def test_lagging_member_preserves_read_your_writes(self):
        # ZooKeeper guarantees a client sees its own writes even through
        # a lagging follower (the follower applies the commit before
        # acking it).
        async with ZKEnsemble(2) as ens:
            writer = await ZKClient([ens.addresses[0]]).connect()
            lagged = await ZKClient([ens.addresses[1]]).connect()
            try:
                await writer.create("/ryw", b"w0")
                ens.set_lag(1, 60_000)
                await writer.put("/ryw", b"w1")
                assert (await lagged.get("/ryw"))[0] == b"w0"  # stale
                await lagged.create("/ryw/own", b"")  # own write
                # The own write caught the member up past w1 too.
                assert (await lagged.get("/ryw"))[0] == b"w1"
                assert await lagged.get_children("/ryw") == ["own"]
            finally:
                await lagged.close()
                await writer.close()

    async def test_quiescence_catches_a_lagging_member_up(self):
        # Without sync(), a lagging member applies its backlog once the
        # commit stream has been quiet for apply_delay_ms.
        async with ZKEnsemble(2, tick_ms=20) as ens:
            writer = await ZKClient([ens.addresses[0]]).connect()
            reader = await ZKClient([ens.addresses[1]]).connect()
            try:
                await writer.create("/q", b"old")
                ens.set_lag(1, 100)
                await writer.put("/q", b"new")
                assert (await reader.get("/q"))[0] == b"old"
                for _ in range(100):
                    await asyncio.sleep(0.05)
                    if (await reader.get("/q"))[0] == b"new":
                        break
                else:
                    raise AssertionError("lagging member never caught up")
            finally:
                await reader.close()
                await writer.close()

    async def test_watch_armed_on_stale_view_fires_on_catch_up(self):
        # A watch armed through a lagging member may guard a transition
        # that already committed (its event fired before the watch
        # existed).  Real ZK delivers it when the follower applies the
        # txn; here, when the member catches up.
        async with ZKEnsemble(2) as ens:
            writer = await ZKClient([ens.addresses[0]]).connect()
            reader = await ZKClient([ens.addresses[1]]).connect()
            try:
                await reader.create("/warm", b"")  # any node, pre-lag
                ens.set_lag(1, 60_000)
                await writer.create("/x", b"")  # freezes member 1
                await writer.create("/y", b"")

                created = asyncio.Event()
                reader.watch("/x", lambda ev: created.set())
                # Stale view: /x not there yet; arms an exist watch.
                assert await reader.exists("/x", watch=True) is None

                deleted = asyncio.Event()
                reader.watch("/warm", lambda ev: deleted.set())
                await writer.unlink("/warm")
                # Stale view still shows /warm; arms a data watch whose
                # NODE_DELETED already fired on the live tree.
                assert await reader.exists("/warm", watch=True) is not None

                await reader.sync("/")  # catch-up reconciles both
                await asyncio.wait_for(created.wait(), timeout=2)
                await asyncio.wait_for(deleted.wait(), timeout=2)
                assert await reader.exists("/x") is not None
                assert await reader.exists("/warm") is None
            finally:
                await reader.close()
                await writer.close()

    async def test_data_and_child_watches_owed_changes_fire_on_catch_up(self):
        # The remaining two reconciliation shapes: a data watch armed on
        # the stale view whose node changed (mzxid diff -> DATA_CHANGED)
        # and a child watch whose node gained a child (cversion diff ->
        # CHILDREN_CHANGED).
        async with ZKEnsemble(2) as ens:
            writer = await ZKClient([ens.addresses[0]]).connect()
            reader = await ZKClient([ens.addresses[1]]).connect()
            try:
                await writer.create("/d", b"v0")
                await writer.mkdirp("/p")
                ens.set_lag(1, 60_000)
                await writer.set_data("/d", b"v1")  # freezes member 1
                await writer.create("/p/kid", b"")

                data_ev, child_ev = [], []
                reader.watch("/d", lambda ev: data_ev.append(ev.type))
                # stale read arms the data watch (still sees v0)
                assert (await reader.get("/d", watch=True))[0] == b"v0"
                reader.watch("/p", lambda ev: child_ev.append(ev.type))
                assert await reader.get_children("/p", watch=True) == []

                await reader.sync("/")  # catch-up reconciles both
                for _ in range(200):
                    if data_ev and child_ev:
                        break
                    await asyncio.sleep(0.01)
                assert data_ev == [EventType.NODE_DATA_CHANGED]
                assert child_ev == [EventType.NODE_CHILDREN_CHANGED]
                # post-catch-up reads are current
                assert (await reader.get("/d"))[0] == b"v1"
                assert await reader.get_children("/p") == ["kid"]
            finally:
                await reader.close()
                await writer.close()

    async def test_child_watch_on_deleted_parent_fires_deleted_on_catch_up(self):
        async with ZKEnsemble(2) as ens:
            writer = await ZKClient([ens.addresses[0]]).connect()
            reader = await ZKClient([ens.addresses[1]]).connect()
            try:
                await writer.mkdirp("/gone")
                ens.set_lag(1, 60_000)
                await writer.unlink("/gone")  # freezes member 1

                events = []
                reader.watch("/gone", lambda ev: events.append(ev.type))
                # stale view still shows the node; arms a child watch
                assert await reader.get_children("/gone", watch=True) == []

                await reader.sync("/")
                for _ in range(200):
                    if events:
                        break
                    await asyncio.sleep(0.01)
                assert events == [EventType.NODE_DELETED]
            finally:
                await reader.close()
                await writer.close()

    async def test_watch_fired_live_is_not_redelivered_on_catch_up(self):
        # One-shot semantics: a watch armed while lagging that the live
        # commit path already fired must not fire a second time when the
        # member catches up.
        async with ZKEnsemble(2) as ens:
            writer = await ZKClient([ens.addresses[0]]).connect()
            reader = await ZKClient([ens.addresses[1]]).connect()
            try:
                await reader.create("/seed", b"")
                ens.set_lag(1, 60_000)
                await writer.put("/seed", b"freeze")  # member 1 freezes
                events = []
                reader.watch("/x", events.append)
                # /x absent in both views; arms a live exist watch.
                assert await reader.exists("/x", watch=True) is None
                await writer.create("/x", b"")  # fires the watch live
                for _ in range(50):
                    if events:
                        break
                    await asyncio.sleep(0.02)
                assert len(events) == 1
                await reader.sync("/")  # catch-up must not re-deliver
                await asyncio.sleep(0.2)
                assert len(events) == 1
            finally:
                await reader.close()
                await writer.close()

    async def test_exists_watch_owed_a_create_that_was_already_deleted(self):
        # A node created AND deleted inside the lag window, with the
        # exists watch armed afterwards against the stale view: the
        # stale/live diff shows nothing, but a real follower applying
        # the backlog fires NODE_CREATED for the armed watch (round-4
        # advisor finding — the create log closes the gap).
        async with ZKEnsemble(2) as ens:
            writer = await ZKClient([ens.addresses[0]]).connect()
            reader = await ZKClient([ens.addresses[1]]).connect()
            try:
                ens.set_lag(1, 60_000)
                await writer.put("/seed", b"freeze")  # member 1 freezes
                await writer.create("/ctd", b"")  # both transitions land
                await writer.unlink("/ctd")  # inside the backlog
                events = []
                reader.watch("/ctd", events.append)
                # Stale view: never saw /ctd; arms an exist watch.
                assert await reader.exists("/ctd", watch=True) is None
                await reader.sync("/")  # catch-up owes the create event
                for _ in range(100):
                    if events:
                        break
                    await asyncio.sleep(0.02)
                assert [e.type for e in events] == [EventType.NODE_CREATED]
            finally:
                await reader.close()
                await writer.close()

    async def test_delete_and_recreate_in_lag_window_fires_deleted(self):
        # The node existed in the frozen view, then was deleted AND
        # recreated inside the lag window.  The first backlog event the
        # armed (one-shot) data watch is owed is NODE_DELETED — a plain
        # mzxid diff would mislabel it NODE_DATA_CHANGED and promise the
        # node still exists at a moment the real history had it gone.
        async with ZKEnsemble(2) as ens:
            writer = await ZKClient([ens.addresses[0]]).connect()
            reader = await ZKClient([ens.addresses[1]]).connect()
            try:
                await writer.create("/dr", b"v0")
                ens.set_lag(1, 60_000)
                await writer.put("/seed", b"freeze")  # member 1 freezes
                await writer.unlink("/dr")
                await writer.create("/dr", b"v1")  # same path, new node
                events = []
                reader.watch("/dr", events.append)
                # Stale view still shows the original node; arms a data
                # watch whose guarded transitions already committed.
                assert (await reader.get("/dr", watch=True))[0] == b"v0"
                await reader.sync("/")
                for _ in range(100):
                    if events:
                        break
                    await asyncio.sleep(0.02)
                assert [e.type for e in events] == [EventType.NODE_DELETED]
            finally:
                await reader.close()
                await writer.close()

    async def test_write_multi_via_lagging_member_stamps_applied_zxid(self):
        # Like CREATE/DELETE/SETDATA, a write multi served by a lagging
        # member catches the member up BEFORE the reply is encoded: the
        # client's last_zxid must cover its own commit, or the
        # connect-time zxid-refusal guard cannot protect read-your-writes
        # across a reconnect (round-4 advisor finding).
        async with ZKEnsemble(2) as ens:
            writer = await ZKClient([ens.addresses[0]]).connect()
            reader = await ZKClient([ens.addresses[1]]).connect()
            try:
                ens.set_lag(1, 60_000)
                await writer.put("/seed", b"freeze")  # member 1 freezes
                await reader.multi([Op.create("/via-multi", b"")])
                assert reader.last_zxid == ens.state.zxid
            finally:
                await reader.close()
                await writer.close()

    async def test_lagging_member_reports_its_applied_zxid(self):
        # A real follower stamps replies with its own lastProcessedZxid.
        # If a lagging member stamped the live shared zxid instead, the
        # client's last_zxid would overstate what it observed and the
        # SetWatches reconciliation after a reconnect would be
        # suppressed for changes it never saw.
        async with ZKEnsemble(2) as ens:
            writer = await ZKClient([ens.addresses[0]]).connect()
            reader = await ZKClient([ens.addresses[1]]).connect()
            try:
                await writer.create("/zx", b"v1")
                await reader.sync("/")
                base = reader.last_zxid
                ens.set_lag(1, 60_000)
                await writer.put("/zx", b"v2")
                assert (await reader.get("/zx"))[0] == b"v1"
                assert reader.last_zxid == base  # not the live zxid
                await reader.sync("/")
                assert (await reader.get("/zx"))[0] == b"v2"
                assert reader.last_zxid > base
            finally:
                await reader.close()
                await writer.close()

    async def test_setwatches_rearm_not_enrolled_for_catch_up(self):
        # A watch re-armed via the SET_WATCHES reconnect handler was
        # already reconciled against the live tree (relative_zxid); if
        # catch-up reconciled it again, the client could receive an
        # event for a transition it already observed.
        async with ZKEnsemble(2) as ens:
            writer = await ZKClient([ens.addresses[0]]).connect()
            # pinned to member 1 so the reconnect lands there again
            reader = await ZKClient([ens.addresses[1]]).connect()
            try:
                await reader.create("/w", b"")
                events = []
                reader.watch("/w", events.append)
                assert await reader.exists("/w", watch=True) is not None
                ens.set_lag(1, 60_000)
                await writer.create("/other", b"")  # freezes member 1
                member = ens.servers[1]
                assert member._lag_root is not None

                await member.drop_connections()
                for _ in range(100):  # reconnect + SetWatches re-arm
                    try:
                        if await reader.exists("/w") is not None:
                            break
                    except Exception:  # noqa: BLE001 - still reconnecting
                        pass
                    await asyncio.sleep(0.05)
                assert all(
                    path != "/w" for _, path, _ in member._lag_watches
                ), "SetWatches re-arm must not enroll in lag reconciliation"
                await reader.sync("/")
                await asyncio.sleep(0.2)
                assert events == []  # no phantom notification
            finally:
                await reader.close()
                await writer.close()

    async def test_lagging_member_refuses_client_from_the_future(self):
        # Real ZooKeeper refuses a session whose client has seen a newer
        # zxid than the server (closing the connection with no
        # ConnectResponse); otherwise the member's stale reply stamps
        # would rewind the client's last_zxid and later reconnects would
        # re-deliver watch events it already observed.
        import struct

        from registrar_tpu.zk.jute import Writer
        from registrar_tpu.zk.protocol import ConnectRequest, frame

        async with ZKEnsemble(2) as ens:
            writer = await ZKClient([ens.addresses[0]]).connect()
            try:
                await writer.create("/f", b"v1")
                ens.set_lag(1, 60_000)
                await writer.put("/f", b"v2")  # freezes member 1 behind
                live_zxid = writer.last_zxid

                async def handshake(addr, last_seen):
                    r, w = await asyncio.open_connection(*addr)
                    try:
                        req = ConnectRequest(
                            timeout_ms=5000, last_zxid_seen=last_seen
                        )
                        jw = Writer()
                        req.write(jw)
                        w.write(frame(jw.to_bytes()))
                        await w.drain()
                        # bounded: a refusal closes the conn (EOF ->
                        # IncompleteReadError); never block the suite on
                        # a reply that may not come
                        hdr = await asyncio.wait_for(r.readexactly(4), 10)
                        length = struct.unpack(">i", hdr)[0]
                        return await asyncio.wait_for(
                            r.readexactly(length), 10
                        )
                    finally:
                        w.close()

                # The caught-up member accepts the client.
                reply = await handshake(ens.addresses[0], live_zxid)
                assert struct.unpack(">q", reply[8:16])[0] != 0  # session id
                # The lagging member refuses it: EOF, no ConnectResponse.
                with pytest.raises(asyncio.IncompleteReadError):
                    await handshake(ens.addresses[1], live_zxid)
                # ... but accepts a client at or behind its view.
                reply = await handshake(ens.addresses[1], 0)
                assert struct.unpack(">q", reply[8:16])[0] != 0
            finally:
                await writer.close()

    async def test_client_fails_over_past_a_refusing_lagging_member(self):
        # A client ahead of a lagging member (it observed a commit
        # through the fresh member) is refused by the laggard at connect
        # and must transparently land on a member that can serve it —
        # the reconnect loop absorbing the refusal is what makes the
        # refusal guard deployable.
        from registrar_tpu.retry import RetryPolicy

        fast = RetryPolicy(
            max_attempts=float("inf"), initial_delay=0.02, max_delay=0.2
        )
        async with ZKEnsemble(2) as ens:
            client = ZKClient(ens.addresses, reconnect_policy=fast)
            await client.connect()
            try:
                await client.create("/ff", b"v0")
                ens.set_lag(1, 60_000)
                # Each write bumps the live zxid; if we're on member 1
                # the write catches it up, so the next write (via
                # whichever member) still leaves client.last_zxid at the
                # live zxid and member 1 frozen whenever we're on 0.
                # A refusal needs (client on member 0 at drop) AND (the
                # reconnect shuffle trying member 1 first) — roughly one
                # cycle in four — so loop until one is observed, bounded
                # at 60 cycles (P(none) < 1e-7) with a minimum of 5
                # cycles of pure failover exercise.
                cycle = 0
                while cycle < 60 and (
                    cycle < 5 or ens.servers[1].refused_count == 0
                ):
                    await client.put("/ff", f"v{cycle}".encode())
                    holder = ens.servers[
                        member_holding(ens, client.session_id)
                    ]
                    await holder.drop_connections()
                    # Reconnect may try the laggard first (refused, EOF)
                    # before landing somewhere serviceable; in-flight ops
                    # fail fast with CONNECTION_LOSS while it settles, so
                    # read like a real caller: retry the op.
                    for _ in range(200):
                        try:
                            data, _ = await client.get("/ff")
                            break
                        except ZKError:
                            await asyncio.sleep(0.02)
                    else:
                        raise AssertionError(
                            f"cycle {cycle}: client never reconnected"
                        )
                    # wherever it landed, its view serves its zxid
                    assert data == f"v{cycle}".encode()
                    cycle += 1
                assert ens.servers[1].refused_count >= 1
            finally:
                await client.close()

    async def test_set_lag_zero_catches_up_immediately(self):
        async with ZKEnsemble(2) as ens:
            writer = await ZKClient([ens.addresses[0]]).connect()
            reader = await ZKClient([ens.addresses[1]]).connect()
            try:
                await writer.create("/z", b"old")
                ens.set_lag(1, 60_000)
                await writer.put("/z", b"new")
                assert (await reader.get("/z"))[0] == b"old"
                ens.set_lag(1, 0)
                assert (await reader.get("/z"))[0] == b"new"
            finally:
                await reader.close()
                await writer.close()


async def test_lag_reads_are_historical_prefixes_and_monotonic():
    """Property sweep over random schedules of writes, lag toggles,
    syncs, and reads: a read through the (possibly lagging) member must
    always return a value that actually existed (a historical prefix
    state, never an invention), the member's view must be monotonic
    (catch-up only moves forward), a read right after sync() must be
    current, and reads through the never-lagging member are always
    current.  Failing seed printed for reproduction."""
    import random

    async def one_schedule(seed: int) -> None:
        rng = random.Random(seed)
        async with ZKEnsemble(2) as ens:
            w = await ZKClient([ens.addresses[0]]).connect()
            r = await ZKClient([ens.addresses[1]]).connect()
            try:
                await w.create("/p", b"0")
                await r.sync("/")
                writes = [b"0"]  # every value /p has ever held, in order
                last_seen = 0  # newest index the reader has observed
                for _ in range(rng.randrange(8, 16)):
                    roll = rng.random()
                    if roll < 0.40:
                        val = str(len(writes)).encode()
                        await w.put("/p", val)
                        writes.append(val)
                    elif roll < 0.52:
                        ens.set_lag(1, 60_000)
                    elif roll < 0.64:
                        ens.set_lag(1, 0)
                    elif roll < 0.80:
                        await r.sync("/")
                        data = (await r.get("/p"))[0]
                        assert data == writes[-1], (seed, data, writes)
                        last_seen = len(writes) - 1
                    else:
                        data = (await r.get("/p"))[0]
                        idx = writes.index(data)  # ValueError = invented
                        assert idx >= last_seen, (seed, idx, last_seen)
                        last_seen = idx
                    # the never-lagging member is always current
                    assert (await w.get("/p"))[0] == writes[-1], seed
            finally:
                await r.close()
                await w.close()

    base = int(os.environ.get("LAG_PROP_SEED", random.randrange(2**31)))
    print(f"LAG_PROP_SEED={base}", file=sys.stderr)
    for i in range(20):
        await one_schedule(base + i)


async def test_dead_member_rejected_as_snapshot_donor():
    # A killed member's state IS the live ensemble's shared state;
    # adopting it as a snapshot donor would alias (and partially wipe)
    # the running ensemble.  ZKEnsemble.restart() is the rejoin path.
    import pytest

    async with ZKEnsemble(2) as ens:
        victim = ens.servers[0]
        await ens.kill(0)
        with pytest.raises(ValueError, match="ensemble member"):
            ZKServer(snapshot=victim)
        await ens.restart(0)  # the supported path still works
        assert len(ens.live) == 2


async def test_standalone_server_unaffected_by_ensemble_changes():
    # Regression guard for the shared-state refactor: two standalone
    # servers must not share anything.
    a = await ZKServer().start()
    b = await ZKServer().start()
    try:
        ca = await ZKClient([a.address]).connect()
        cb = await ZKClient([b.address]).connect()
        try:
            await ca.create("/only-a", b"")
            assert a.get_node("/only-a") is not None
            assert b.get_node("/only-a") is None
        finally:
            await ca.close()
            await cb.close()
    finally:
        await a.stop()
        await b.stop()


class TestQuorum:
    """ISSUE 10: the real replication protocol — elected leader, quorum
    commit gate, read-only minority mode, elections with a window, and
    the client armor that rides through all of it."""

    async def test_roles_elected_leader_and_followers(self):
        async with ZKEnsemble(3) as ens:
            assert [m.mode for m in ens.live] == [
                "leader", "follower", "follower"
            ]
            assert ens.leader_index == 0
            assert ens.has_quorum

    async def test_leader_kill_reelects_most_caught_up_member(self):
        async with ZKEnsemble(3) as ens:
            await ens.kill(0)
            assert ens.leader_index == 1
            assert ens.state.elections >= 2  # initial + failover
            # a rejoining member does NOT dethrone the new leader
            await ens.restart(0)
            assert ens.leader_index == 1
            assert ens.servers[0].mode == "follower"

    async def test_session_reattaches_across_leader_election(self):
        # Satellite 3: the client's session (and its ephemerals) survive
        # a leader election with a real election window.
        async with ZKEnsemble(3, election_ms=100, tick_ms=10) as ens:
            from registrar_tpu.retry import RetryPolicy

            fast = RetryPolicy(
                max_attempts=float("inf"), initial_delay=0.02, max_delay=0.2
            )
            client = ZKClient(
                ens.addresses, timeout_ms=60_000, reconnect_policy=fast
            )
            await client.connect()
            try:
                await client.create("/elect", b"x", CreateFlag.EPHEMERAL)
                sid = client.session_id
                leader = ens.leader_index
                await ens.kill(leader)
                # mid-election there is no leader ...
                assert ens.leader_index is None
                # ... and the ephemeral never leaves the replicated tree
                deadline = asyncio.get_event_loop().time() + 10
                while ens.leader_index is None:
                    node = ens.get_node("/elect")
                    assert node is not None and node.ephemeral_owner == sid
                    assert asyncio.get_event_loop().time() < deadline
                    await asyncio.sleep(0.01)
                # same session on a surviving member, writes work again
                deadline = asyncio.get_event_loop().time() + 10
                while True:
                    try:
                        await client.set_data("/elect", b"y")
                        break
                    except ZKError:
                        assert asyncio.get_event_loop().time() < deadline
                        await asyncio.sleep(0.02)
                assert client.session_id == sid
                st = await client.stat("/elect")
                assert st.ephemeral_owner == sid
            finally:
                await client.close()

    async def test_minority_refuses_writes_serves_reads_read_only(self):
        async with ZKEnsemble(3) as ens:
            ro_client = ZKClient(
                ens.addresses, timeout_ms=60_000, can_be_read_only=True,
                reconnect=False,
            )
            await ro_client.connect()
            try:
                await ro_client.create("/ro", b"v1")
                await ens.kill(1)
                await ens.kill(2)
                survivor = ens.servers[0]
                assert survivor.mode == "read-only"
                # the ro-capable client reattaches to the minority member
                direct = ZKClient(
                    [(survivor.host, survivor.port)],
                    timeout_ms=60_000, can_be_read_only=True,
                )
                await direct.connect()
                try:
                    assert direct.read_only
                    # reads answer (zxid-consistent frozen view)
                    data, _ = await direct.get("/ro")
                    assert data == b"v1"
                    # writes refuse with the retryable NOT_READONLY
                    refused = []
                    direct.on("write_refused", refused.append)
                    with pytest.raises(ZKError) as err:
                        await direct.set_data("/ro", b"v2")
                    from registrar_tpu.retry import is_transient
                    from registrar_tpu.zk.protocol import Err

                    assert err.value.code == Err.NOT_READONLY
                    assert is_transient(err.value)
                    assert refused == ["read_only"]
                    assert survivor.writes_refused >= 1
                finally:
                    await direct.close()
            finally:
                await ro_client.close()

    async def test_read_only_member_refuses_non_ro_handshake(self):
        async with ZKEnsemble(3) as ens:
            await ens.kill(1)
            await ens.kill(2)
            survivor = ens.servers[0]
            plain = ZKClient(
                [(survivor.host, survivor.port)],
                timeout_ms=5000, connect_pass_timeout_ms=1500,
                reconnect=False,
            )
            with pytest.raises(Exception):
                await plain.connect()
            assert survivor.refused_ro >= 1
            await plain.close()

    async def test_sessions_frozen_without_quorum_reaped_after(self):
        # No leader -> no session expiry (the session tracker lives on
        # the leader); quorum's return reaps the overdue session.
        async with ZKEnsemble(3, tick_ms=10) as ens:
            client = await ZKClient(
                ens.addresses, timeout_ms=200, reconnect=False
            ).connect()
            await client.create("/frozen", b"", CreateFlag.EPHEMERAL)
            sid = client.session_id
            await ens.kill(1)
            await ens.kill(2)
            await client.close()  # disconnected; countdown starts
            await asyncio.sleep(0.8)  # way past the negotiated timeout
            assert sid in ens.state.sessions  # frozen, not expired
            assert ens.get_node("/frozen") is not None
            await ens.restart(1)  # quorum returns -> leader sweeps
            deadline = asyncio.get_event_loop().time() + 5
            while sid in ens.state.sessions:
                assert asyncio.get_event_loop().time() < deadline
                await asyncio.sleep(0.02)
            assert ens.get_node("/frozen") is None

    async def test_registration_during_quorum_loss_retries_clean(self):
        # The acceptance case: a write refused during quorum loss is
        # retried via the existing transient-retry path and lands once
        # quorum returns — zero duplicate znodes, same session.
        from registrar_tpu.retry import RetryPolicy

        async with ZKEnsemble(3, tick_ms=10) as ens:
            client = ZKClient(
                ens.addresses, timeout_ms=60_000, can_be_read_only=True,
                reconnect_policy=RetryPolicy(
                    max_attempts=float("inf"), initial_delay=0.02,
                    max_delay=0.2,
                ),
            )
            client.rw_probe_interval_s = 0.05
            await client.connect()
            try:
                await ens.kill(1)
                await ens.kill(2)
                sid = client.session_id
                retry = RetryPolicy(
                    max_attempts=200, initial_delay=0.02, max_delay=0.2
                )
                task = asyncio.ensure_future(
                    register(
                        zk=client,
                        registration={
                            "domain": "q.loss.us", "type": "load_balancer"
                        },
                        admin_ip="10.3.0.1",
                        hostname="qhost",
                        settle_delay=0,
                        retry_policy=retry,
                    )
                )
                await asyncio.sleep(0.3)  # refusals accumulate meanwhile
                assert not task.done()
                await ens.restart(1)  # quorum returns
                znodes = await asyncio.wait_for(task, timeout=15)
                # same session did the work; zero duplicates
                assert client.session_id == sid
                host_nodes = [p for p in znodes if p.endswith("/qhost")]
                assert len(host_nodes) == 1
                node = ens.get_node(host_nodes[0])
                assert node is not None and node.ephemeral_owner == sid
                parent = ens.get_node("/us/loss/q")
                assert sorted(parent.children) == ["qhost"]
                refused = sum(
                    m.writes_refused for m in ens.servers if m is not None
                )
                assert refused >= 1  # the refusal path was exercised
            finally:
                await client.close()

    async def test_rw_probe_fails_over_from_read_only_member(self):
        from registrar_tpu.retry import RetryPolicy

        async with ZKEnsemble(3, tick_ms=10) as ens:
            client = ZKClient(
                ens.addresses, timeout_ms=60_000, can_be_read_only=True,
                reconnect_policy=RetryPolicy(
                    max_attempts=float("inf"), initial_delay=0.02,
                    max_delay=0.2,
                ),
            )
            client.rw_probe_interval_s = 0.05
            await client.connect()
            try:
                await ens.kill(1)
                await ens.kill(2)
                deadline = asyncio.get_event_loop().time() + 10
                while not (client.connected and client.read_only):
                    assert asyncio.get_event_loop().time() < deadline
                    await asyncio.sleep(0.02)
                await ens.restart(1)
                await ens.restart(2)
                # the probe notices rw members and moves the session
                deadline = asyncio.get_event_loop().time() + 10
                while not (client.connected and not client.read_only):
                    assert asyncio.get_event_loop().time() < deadline
                    await asyncio.sleep(0.02)
                await client.create("/back", b"rw")  # writes work again
            finally:
                await client.close()

    async def test_partition_minority_stale_reads_heal_catches_up(self):
        async with ZKEnsemble(3) as ens:
            writer = await ZKClient([ens.addresses[0]]).connect()
            reader = ZKClient(
                [ens.addresses[2]], timeout_ms=60_000,
                can_be_read_only=True, reconnect=False,
            )
            await reader.connect()
            try:
                await writer.create("/part", b"v1")
                ens.partition([[0, 1], [2]])
                assert ens.servers[2].mode == "read-only"
                assert ens.leader_index == 0
                # majority serves writes; the minority's view is frozen
                await writer.set_data("/part", b"v2")
                ro = ZKClient(
                    [ens.addresses[2]], timeout_ms=60_000,
                    can_be_read_only=True, reconnect=False,
                )
                await ro.connect()
                try:
                    assert ro.read_only
                    assert (await ro.get("/part"))[0] == b"v1"  # stale
                finally:
                    await ro.close()
                ens.heal_partition()
                assert ens.servers[2].mode == "follower"
                # healed member caught up (counted as backlog replay)
                assert ens.servers[2].catchup_replayed >= 1
                direct = await ZKClient(
                    [ens.addresses[2]], reconnect=False
                ).connect()
                try:
                    assert (await direct.get("/part"))[0] == b"v2"
                finally:
                    await direct.close()
            finally:
                await reader.close()
                await writer.close()

    async def test_restart_catchup_replay_vs_snapshot(self):
        # A member back within the backlog replays the committed diff;
        # one whose departure fell off the bounded backlog snapshots.
        async with ZKEnsemble(3, backlog_max=4) as ens:
            client = await ZKClient([ens.addresses[0]]).connect()
            try:
                await ens.kill(2)
                await client.create("/c1", b"")
                await client.create("/c2", b"")
                member = await ens.restart(2)
                assert member.catchup_replayed == 2
                assert member.catchup_snapshots == 0

                await ens.kill(2)
                for i in range(8):  # > backlog_max: tail truncated
                    await client.create(f"/s{i}", b"")
                member = await ens.restart(2)
                assert member.catchup_snapshots == 1
            finally:
                await client.close()

    async def test_4lw_reports_role_quorum_and_applied_zxid(self):
        async def probe(member, word):
            reader, writer = await asyncio.open_connection(
                member.host, member.port
            )
            writer.write(word.encode())
            await writer.drain()
            out = await asyncio.wait_for(reader.read(1 << 20), timeout=5)
            writer.close()
            return out.decode()

        async with ZKEnsemble(3) as ens:
            srvr = await probe(ens.servers[0], "srvr")
            assert "Mode: leader" in srvr
            assert "Quorum size: 2" in srvr
            assert "Ensemble size: 3" in srvr
            assert "Mode: follower" in await probe(ens.servers[1], "srvr")
            mntr = dict(
                line.split("\t", 1)
                for line in (await probe(ens.servers[1], "mntr")).splitlines()
                if line
            )
            assert mntr["zk_server_state"] == "follower"
            assert mntr["zk_quorum_size"] == "2"
            assert "zk_applied_zxid" in mntr
            assert await probe(ens.servers[1], "isro") == "rw"
            # degrade to minority: role flips everywhere it is reported
            await ens.kill(1)
            await ens.kill(2)
            assert await probe(ens.servers[0], "isro") == "ro"
            assert "Mode: read-only" in await probe(ens.servers[0], "srvr")
            mntr = dict(
                line.split("\t", 1)
                for line in (await probe(ens.servers[0], "mntr")).splitlines()
                if line
            )
            assert mntr["zk_server_state"] == "read-only"

    async def test_leader_kill_mid_registration_e2e(self):
        # THE acceptance e2e: SIGKILL-shaped leader death while the
        # registration pipeline is in flight; the same session converges
        # with zero orphan/duplicate znodes and a measurable gap.
        from registrar_tpu.retry import RetryPolicy

        async with ZKEnsemble(3, election_ms=80, tick_ms=10) as ens:
            client = ZKClient(
                ens.addresses, timeout_ms=60_000,
                reconnect_policy=RetryPolicy(
                    max_attempts=float("inf"), initial_delay=0.02,
                    max_delay=0.2,
                ),
            )
            await client.connect()
            try:
                sid = client.session_id
                retry = RetryPolicy(
                    max_attempts=200, initial_delay=0.02, max_delay=0.2
                )
                task = asyncio.ensure_future(
                    register(
                        zk=client,
                        registration={
                            "domain": "mid.kill.us", "type": "load_balancer"
                        },
                        admin_ip="10.4.0.1",
                        hostname="midhost",
                        settle_delay=0.05,  # keeps the pipeline window open
                        retry_policy=retry,
                    )
                )
                await asyncio.sleep(0.02)  # mid-pipeline ...
                await ens.kill(ens.leader_index)  # ... the leader dies
                znodes = await asyncio.wait_for(task, timeout=15)
                assert client.session_id == sid  # same session
                host = [p for p in znodes if p.endswith("/midhost")][0]
                node = ens.get_node(host)
                assert node is not None and node.ephemeral_owner == sid
                # zero duplicates/orphans anywhere under the domain
                parent = ens.get_node("/us/kill/mid")
                assert sorted(parent.children) == ["midhost"]
                for child in parent.children.values():
                    owner = child.ephemeral_owner
                    assert owner in (0, sid)
                await client.heartbeat(znodes)  # liveness post-failover
            finally:
                await client.close()

    async def test_rolling_restart_zero_no_node_from_polling_resolver(self):
        # Full rolling restart of every member; a 10 ms polling resolver
        # must never observe NO_NODE (missing records) — transient
        # connection losses during its own failover are retried, never
        # counted: the DNS answer, whenever readable, is always whole.
        from registrar_tpu import binderview
        from registrar_tpu.retry import RetryPolicy

        fast = RetryPolicy(
            max_attempts=float("inf"), initial_delay=0.02, max_delay=0.2
        )
        async with ZKEnsemble(3, election_ms=60, tick_ms=10) as ens:
            agent = ZKClient(
                ens.addresses, timeout_ms=60_000, reconnect_policy=fast,
            )
            await agent.connect()
            resolver = ZKClient(
                ens.addresses, timeout_ms=60_000, reconnect_policy=fast,
                can_be_read_only=True,
            )
            await resolver.connect()
            try:
                znodes = await register(
                    zk=agent,
                    registration={
                        "domain": "roll.e2e.us",
                        "type": "load_balancer",
                        # the service record makes the domain resolvable
                        # (the Binder A-answer the poller watches)
                        "service": {
                            "type": "service",
                            "service": {
                                "srvce": "_http", "proto": "_tcp",
                                "port": 80,
                            },
                        },
                    },
                    admin_ip="10.5.0.1",
                    hostname="rollhost",
                    settle_delay=0,
                )
                sid = agent.session_id
                stop = asyncio.Event()
                no_node = []
                answers = [0]

                async def poll():
                    while not stop.is_set():
                        try:
                            res = await binderview.resolve(
                                resolver, "roll.e2e.us", "A"
                            )
                            if not res.answers:
                                no_node.append("empty")
                            else:
                                answers[0] += 1
                        except ZKError as err:
                            from registrar_tpu.zk.protocol import Err

                            if err.code == Err.NO_NODE:
                                no_node.append(err.name)
                            # transient wire errors: the resolver retries
                        except (ConnectionError, OSError):
                            pass
                        await asyncio.sleep(0.01)

                poller = asyncio.create_task(poll())
                # the rolling restart: one member at a time, quorum held
                for i in range(3):
                    await ens.kill(i)
                    await asyncio.sleep(0.25)
                    await ens.restart(i)
                    await asyncio.sleep(0.25)
                stop.set()
                await poller
                assert not no_node, f"resolver saw NO_NODE: {no_node}"
                assert answers[0] > 10  # the poller genuinely sampled
                # the registration survived the whole upgrade untouched
                assert agent.session_id == sid
                await agent.heartbeat(znodes)
            finally:
                await resolver.close()
                await agent.close()

    async def test_connect_order_is_seedable(self):
        # Satellite: rng= makes the connect-order shuffle deterministic
        # per seed (chaos storms pin CHAOS_SEED through this).
        import random as random_mod

        async with ZKEnsemble(3) as ens:
            expected = list(ens.addresses)
            random_mod.Random(7).shuffle(expected)
            client = ZKClient(
                ens.addresses, reconnect=False, rng=random_mod.Random(7)
            )
            await client.connect()
            try:
                assert client.connected_server == expected[0]
            finally:
                await client.close()


    async def test_ro_hunting_connect_adopts_not_orphans_sessions(self):
        # A fresh ro-capable client whose connect pass hunts past a
        # read-only member must ADOPT the session that handshake
        # established and reattach it at the fallback — not mint one
        # session per refused member (orphans that leader-only expiry
        # could never reap while quorum is lost).
        async with ZKEnsemble(3) as ens:
            await ens.kill(1)
            await ens.kill(2)
            before = set(ens.state.sessions)
            client = ZKClient(
                ens.addresses, timeout_ms=60_000, can_be_read_only=True,
                reconnect=False,
            )
            await client.connect()
            try:
                assert client.read_only
                new = set(ens.state.sessions) - before
                assert new == {client.session_id}, (
                    f"connect pass left extra sessions: {new}"
                )
            finally:
                await client.close()


    async def test_close_session_refused_without_quorum(self):
        # closeSession is a quorum transaction too: a read-only minority
        # member must NOT commit the ephemeral deletes — the session and
        # its znodes stay frozen until a leader (quorum) expires them.
        async with ZKEnsemble(3, tick_ms=10) as ens:
            client = ZKClient(
                ens.addresses, timeout_ms=300, can_be_read_only=True,
                reconnect=False,
            )
            await client.connect()
            await client.create("/frozen-close", b"", CreateFlag.EPHEMERAL)
            sid = client.session_id
            await ens.kill(1)
            await ens.kill(2)
            # reattach read-only, then try a clean close
            ro = ZKClient(
                [ens.addresses[0]], timeout_ms=300, can_be_read_only=True,
                reconnect=False,
            )
            ro.seed_session(
                sid, client.session_passwd, negotiated_timeout_ms=300
            )
            await ro.connect()
            assert ro.read_only
            await ro.close()  # best-effort: the refusal is swallowed
            await client.close()
            # the minority never committed the close
            assert sid in ens.state.sessions
            assert ens.get_node("/frozen-close") is not None
            assert ens.servers[0].writes_refused >= 1
            # quorum returns: the leader expires the overdue session
            await ens.restart(1)
            deadline = asyncio.get_event_loop().time() + 5
            while ens.get_node("/frozen-close") is not None:
                assert asyncio.get_event_loop().time() < deadline
                await asyncio.sleep(0.02)
            assert sid not in ens.state.sessions
