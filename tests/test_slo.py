"""Tests for the availability-SLO simulator (ISSUE 9).

Two layers, mirroring the module:

  * the SLO *math* — availability/nines, outage-window extraction and
    merging, MTTD/MTTR attribution — pinned on synthetic probe
    timelines with no fleet at all (the satellite-task contract:
    overlapping faults must never double-count downtime, and each
    overlapping fault class still gets its own MTTD/MTTR from its own
    injection stamp);
  * the *harness* — a miniature trace against a real in-process fleet
    proves the probe actually detects injected outages (a
    repair-disabled run measurably drops the nines) and that the
    report/metrics surfaces carry what tools/slo.py gates on.
"""

import pytest

from registrar_tpu import metrics as metrics_mod
from registrar_tpu.events import EventEmitter
from registrar_tpu.testing import slo
from registrar_tpu.testing.slo import (
    FaultEvent,
    Probe,
    attribute,
    availability,
    fault_summary,
    merge_windows,
    nines,
    outage_windows,
    total_outage_s,
    window_owner,
)


def timeline(*states, t0=0.0, dt=1.0):
    """Probes from a compact spec: "ok"/"fail" per tick, 1 s apart."""
    return [
        Probe(t0 + i * dt, state == "ok") for i, state in enumerate(states)
    ]


class TestAvailabilityMath:
    def test_availability_fraction(self):
        probes = timeline("ok", "ok", "fail", "ok")
        assert availability(probes) == 0.75

    def test_empty_timeline_is_an_error_not_perfection(self):
        with pytest.raises(ValueError):
            availability([])

    def test_nines(self):
        assert nines(0.9) == 1.0
        assert nines(0.999) == 3.0
        assert nines(1.0) == slo.MAX_NINES
        assert nines(0.0) == 0.0  # and not -0.0

    def test_nines_rejects_out_of_range(self):
        with pytest.raises(ValueError):
            nines(1.5)
        with pytest.raises(ValueError):
            nines(-0.1)


class TestOutageWindows:
    def test_window_opens_at_first_failure_closes_at_next_ok(self):
        probes = timeline("ok", "fail", "fail", "ok", "ok")
        assert outage_windows(probes) == [(1.0, 3.0)]

    def test_multiple_distinct_windows(self):
        probes = timeline("fail", "ok", "fail", "ok")
        assert outage_windows(probes) == [(0.0, 1.0), (2.0, 3.0)]

    def test_trailing_failure_closes_at_end(self):
        probes = timeline("ok", "fail", "fail")
        assert outage_windows(probes, end=10.0) == [(1.0, 10.0)]
        # default close: the last probe's stamp
        assert outage_windows(probes) == [(1.0, 2.0)]

    def test_all_ok_has_no_windows(self):
        assert outage_windows(timeline("ok", "ok")) == []

    def test_merge_coalesces_overlap_and_adjacency(self):
        merged = merge_windows([(5.0, 7.0), (1.0, 3.0), (2.0, 4.0),
                                (4.0, 4.5)])
        assert merged == [(1.0, 4.5), (5.0, 7.0)]

    def test_total_outage_never_double_counts(self):
        # two "faults" overlapping 2..3: the union is 1..4 = 3 s, not 4
        assert total_outage_s([(1.0, 3.0), (2.0, 4.0)]) == 3.0


class TestAttribution:
    def test_simple_fault_gets_mttd_and_mttr(self):
        probes = timeline("ok", "fail", "fail", "ok")
        fault = FaultEvent("crash-loop", 0, injected_at=0.5)
        attribute([fault], probes)
        assert fault.detected_at == 1.0
        assert fault.recovered_at == 3.0
        assert fault.mttd_s == 0.5
        assert fault.mttr_s == 2.5

    def test_detection_is_bounded_by_the_clear_stamp(self):
        # A fault whose whole outage fell between two probe ticks must
        # read as UNDETECTED — never credited with a later, unrelated
        # scenario's failing probe (which would also steal that
        # window's ownership via earliest-injection-wins).
        probes = timeline("ok", "ok", "ok", "fail", "ok")
        blip = FaultEvent("deploy-wave", 0, injected_at=0.5)
        blip.cleared_at = 1.5  # recovered before any probe failed
        later = FaultEvent("crash-loop", 1, injected_at=2.5)
        per, windows = fault_summary([blip, later], probes)
        assert blip.detected_at is None
        assert per["deploy-wave"]["detected"] == 0
        assert per["deploy-wave"]["outage_s"] == 0.0
        assert windows == [(3.0, 4.0)]
        assert window_owner(windows[0], [blip, later]) is later
        assert per["crash-loop"]["outage_s"] == 1.0

    def test_undetected_fault_stays_unmeasured(self):
        probes = timeline("ok", "ok", "ok")
        fault = FaultEvent("health-flap", 0, injected_at=0.5)
        attribute([fault], probes)
        assert fault.detected_at is None
        assert fault.mttd_s is None
        assert fault.mttr_s is None

    def test_overlapping_faults_share_downtime_but_not_clocks(self):
        """The satellite contract: two fault classes overlapping one
        outage — downtime counted once (the earlier fault owns the
        window), while the later fault still gets MTTD/MTTR from its
        OWN injection stamp."""
        #  t: 0   1     2     3     4     5(ok)
        probes = timeline("ok", "fail", "fail", "fail", "fail", "ok")
        first = FaultEvent("crash-loop", 0, injected_at=0.5)
        second = FaultEvent("expiry-storm", 1, injected_at=2.5)
        per, windows = fault_summary([first, second], probes)
        assert windows == [(1.0, 5.0)]
        # one owner: the earlier injection — downtime is not doubled
        assert window_owner(windows[0], [first, second]) is first
        assert per["crash-loop"]["outage_s"] == 4.0
        assert per["expiry-storm"]["outage_s"] == 0.0
        assert (
            per["crash-loop"]["outage_s"] + per["expiry-storm"]["outage_s"]
            == total_outage_s(windows)
        )
        # ...but the second fault keeps its own clocks
        assert second.detected_at == 3.0
        assert second.recovered_at == 5.0
        assert per["expiry-storm"]["mttd_s_mean"] == 0.5
        assert per["expiry-storm"]["mttr_s_mean"] == 2.5
        assert per["crash-loop"]["mttr_s_mean"] == 4.5

    def test_fault_summary_counts_and_rollups(self):
        probes = timeline("ok", "fail", "ok", "fail", "ok")
        faults = [
            FaultEvent("health-flap", 0, injected_at=0.5),
            FaultEvent("health-flap", 0, injected_at=2.5),
            FaultEvent("deploy-wave", 1, injected_at=4.5),  # never detected
        ]
        per, windows = fault_summary(faults, probes)
        assert per["health-flap"]["injected"] == 2
        assert per["health-flap"]["detected"] == 2
        assert per["health-flap"]["mttd_s_mean"] == 0.5
        assert per["health-flap"]["mttr_s_mean"] == 1.5
        assert per["deploy-wave"] == {
            "injected": 1, "detected": 0, "outage_s": 0.0,
            "mttd_s_mean": None, "mttd_s_max": None,
            "mttr_s_mean": None, "mttr_s_max": None,
        }
        assert len(windows) == 2


class TestInstrumentSlo:
    def test_counters_preseeded_and_fed_from_events(self):
        class FakeHarness(EventEmitter):
            fault_ids = ("crash-loop", "netem-episode")

        harness = FakeHarness()
        reg = metrics_mod.instrument_slo(harness)
        text = reg.render()
        # every documented label set exists before any traffic
        assert 'registrar_slo_probe_total{result="ok"} 0' in text
        assert 'registrar_slo_probe_total{result="fail"} 0' in text
        assert (
            'registrar_slo_outage_seconds_total{fault="crash-loop"} 0'
            in text
        )
        harness.emit("probe", "ok")
        harness.emit("probe", "fail")
        harness.emit("probe", "fail")
        harness.emit("outage", "crash-loop", 1.25)
        text = reg.render()
        assert 'registrar_slo_probe_total{result="ok"} 1' in text
        assert 'registrar_slo_probe_total{result="fail"} 2' in text
        assert (
            'registrar_slo_outage_seconds_total{fault="crash-loop"} 1.25'
            in text
        )


#: a miniature trace: two fault classes, small fleet, ~2 s wall — fast
#: enough for the hermetic suite while still exercising the real fleet,
#: prober, injection, and report pipeline end to end
MINI_SCENARIOS = (
    ("crash-loop", {"crashes": 1, "restart_delay": 0.1}),
    ("health-flap", {"flaps": 1, "down_s": 0.1}),
)


async def _mini_trace(repair: bool, seed: int = 11):
    params = dict(slo.TRACES["quick"])
    harness = slo.SLOHarness(
        members=3,
        seed=seed,
        probe_interval=params["probe_interval"],
        session_timeout_ms=params["session_timeout_ms"],
        repair=repair,
    )
    await harness.start()
    try:
        for fault_id, kwargs in MINI_SCENARIOS:
            await harness.run_scenario(fault_id, **kwargs)
            await harness.settle(0.2)
        await harness.settle(0.2)
        return harness, harness.report(trace_name="mini")
    finally:
        await harness.stop()


class TestHarness:
    async def test_probe_detects_injected_outages(self):
        harness, report = await _mini_trace(repair=True)
        assert report["probes"]["total"] > 20
        assert report["probes"]["fail"] > 0, "no outage ever observed"
        assert 0.0 < report["availability"] < 1.0
        for fid in ("crash-loop", "health-flap"):
            entry = report["faults"][fid]
            assert entry["injected"] == 1
            assert entry["detected"] == 1
            assert entry["mttd_s_mean"] is not None
            assert entry["mttr_s_mean"] is not None
            assert entry["mttr_s_mean"] >= entry["mttd_s_mean"]
            assert 0.0 <= entry["availability"] <= 1.0
        # downtime is attributed without double counting
        assert report["outages"]["downtime_s_total"] == pytest.approx(
            sum(e["outage_s"] for e in report["faults"].values()), abs=1e-3
        )
        # the worst window points into the flight recorder
        worst = report["outages"]["worst"]
        assert worst is not None and worst["trace_ids"]
        recorded = {
            entry.get("trace_id")
            for entry in harness.tracer.dump()["entries"]
        }
        assert set(worst["trace_ids"]) & recorded

    async def test_metrics_counters_track_the_run(self):
        harness, report = await _mini_trace(repair=True)
        probe_total = harness.registry.get("registrar_slo_probe_total")
        assert probe_total.value({"result": "ok"}) == report["probes"]["ok"]
        assert (
            probe_total.value({"result": "fail"})
            == report["probes"]["fail"]
        )
        outage = harness.registry.get("registrar_slo_outage_seconds_total")
        attributed = sum(
            outage.value({"fault": fid}) for fid in slo.FAULT_IDS
        )
        assert attributed == pytest.approx(
            report["outages"]["downtime_s_total"], abs=1e-3
        )

    async def test_repair_disabled_measurably_drops_nines(self):
        """The acceptance-criteria proof: a deliberately broken run
        (repair withheld) must lose nines vs the repaired run of the
        same seed — i.e. the probe detects real outages rather than
        vacuously passing."""
        _h1, repaired = await _mini_trace(repair=True)
        _h2, broken = await _mini_trace(repair=False)
        assert broken["availability"] < repaired["availability"]
        assert repaired["nines"] - broken["nines"] >= 0.2

    async def test_probe_spans_carry_scenario_marks(self):
        harness, _report = await _mini_trace(repair=True)
        probe_spans = [
            entry
            for entry in harness.tracer.dump()["entries"]
            if entry.get("name") == "slo.probe"
        ]
        assert probe_spans
        scenarios = {
            entry["attrs"].get("scenario") for entry in probe_spans
        }
        assert "crash-loop" in scenarios
        # the fault events are stamped with the catalog id
        fault_events = [
            entry
            for entry in harness.tracer.dump()["entries"]
            if entry.get("name") == "slo.fault"
        ]
        assert {e["attrs"]["fault"] for e in fault_events} == {
            "crash-loop", "health-flap",
        }

    async def test_unknown_fault_and_scenario_are_rejected(self):
        harness = slo.SLOHarness(members=2, seed=0)
        with pytest.raises(ValueError):
            harness.inject("made-up-fault")
        with pytest.raises(ValueError):
            await harness.run_scenario("made-up-fault")


class TestRunnerPlumbing:
    def test_quick_trace_covers_every_cataloged_fault_class(self):
        quick = {fid for fid, _kw in slo.TRACES["quick"]["scenarios"]}
        assert quick == set(slo.FAULT_IDS)

    def test_gate_metrics_shape_matches_the_baseline(self):
        import json
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        with open(os.path.join(repo, "SLO_BASELINE.json")) as fh:
            baseline = json.load(fh)
        with open(os.path.join(repo, "SLO_HISTORY.json")) as fh:
            history = json.load(fh)
        # the gated metric set is exactly what the history pins — a
        # metric dropped from the report silently ungates itself
        assert set(history["directions"]) == set(baseline["metrics"])
        import bench

        assert (
            bench.check_baseline(
                history_path=os.path.join(repo, "SLO_HISTORY.json"),
                baseline_path=os.path.join(repo, "SLO_BASELINE.json"),
            )
            == []
        )


class TestEnsembleScenarios:
    """ISSUE 10: the ensemble fault classes against a real 3-member
    quorum ensemble (leader election, read-only minority, catch-up)."""

    async def test_leader_kill_measures_failover_mttr(self):
        harness = slo.SLOHarness(
            members=2, seed=11, probe_interval=0.02,
            session_timeout_ms=800, ensemble=3, election_ms=80.0,
        )
        await harness.start()
        try:
            await harness.run_scenario("leader-kill", kills=1, down_s=0.2)
            await harness.settle(0.3)
            report = harness.report(trace_name="unit")
            entry = report["faults"]["leader-kill"]
            assert entry["injected"] == 1
            assert entry["detected"] == 1
            # the MTTR covers deregister -> election -> recommit
            assert entry["mttr_s_mean"] is not None
            assert entry["mttr_s_mean"] > 0.0
            assert report["ensemble"]["members"] == 3
            assert report["ensemble"]["elections"] >= 2
        finally:
            await harness.stop()

    async def test_quorum_loss_keeps_resolves_answering(self):
        harness = slo.SLOHarness(
            members=2, seed=12, probe_interval=0.02,
            session_timeout_ms=800, ensemble=3, election_ms=50.0,
        )
        await harness.start()
        try:
            await harness.run_scenario("quorum-loss", hold_s=0.5)
            await harness.settle(0.3)
            report = harness.report(trace_name="unit")
            entry = report["faults"]["quorum-loss"]
            assert entry["injected"] == 1
            # The design claim: the registrations never left the
            # (frozen) tree and the prober kept reading through the
            # read-only member.  The only tolerated dip is the probe
            # client's own failover blip onto the survivor — if the
            # probe stream dipped at all, it must have recovered while
            # quorum was STILL lost (resolves answer from ro members),
            # never waited for quorum's return.
            assert entry["availability"] > 0.8
            fault = next(
                f for f in harness.faults if f.fault == "quorum-loss"
            )
            if fault.detected_at is not None:
                assert fault.recovered_at is not None
                assert fault.recovered_at < fault.cleared_at, (
                    "resolves only recovered after quorum returned — "
                    "the read-only path never served"
                )
        finally:
            await harness.stop()

    async def test_ensemble_scenarios_need_an_ensemble(self):
        harness = slo.SLOHarness(members=2, seed=13)
        await harness.start()
        try:
            with pytest.raises(ValueError, match="ensemble"):
                await harness.run_scenario("leader-kill")
        finally:
            await harness.stop()


class TestShardScenarios:
    """ISSUE 12: the sharded-serve-tier fault classes against a real
    2-shard worker-process tier (shards= wires the tier + the
    slice-probe leg into the prober)."""

    async def test_shard_kill_measured_and_siblings_never_blip(self):
        harness = slo.SLOHarness(
            members=2, seed=21, probe_interval=0.02,
            session_timeout_ms=800, shards=2,
        )
        await harness.start()
        try:
            assert len(harness.slice_expected) >= 3
            await harness.settle(0.2)
            await harness.run_scenario("shard-kill", kills=1)
            await harness.settle(0.3)
            report = harness.report(trace_name="unit")
            entry = report["faults"]["shard-kill"]
            assert entry["injected"] == 1
            assert entry["detected"] == 1
            # MTTR covers kill -> supervisor detection -> respawn ->
            # slice answering again (the respawn+warm bound).
            assert entry["mttr_s_mean"] is not None
            assert 0.0 < entry["mttr_s_mean"] < 10.0
            assert report["shards"]["respawns"] == 1
            # The scenario itself asserts zero sibling errors (it
            # raises otherwise); the report carries the evidence.
            assert report["shards"]["slice_errors"] > 0

            # ISSUE 13: the worst-outage entry upgrades from trace IDS
            # to the ASSEMBLED cross-process tree — collected while the
            # workers are still alive, so the failing probe's span
            # chain (slo.probe -> shard.relay, and any worker fragment
            # that survived) is one tree under one trace id.
            await harness.collect_worst_trace(report)
            worst = report["outages"]["worst"]
            tree = worst["trace_tree"]
            assert tree is not None
            assert tree["trace_id"] == worst["trace_ids"][0]
            assert tree["spans"] >= 1
            names = set()

            def walk(node):
                names.add(node["name"])
                for child in node.get("children", ()):
                    walk(child)

            for root in tree["roots"]:
                walk(root)
            assert "slo.probe" in names
            # the probe's shard leg crossed the wire: the relay span
            # (recorded by the router, which shares the harness tracer)
            # is in the SAME tree
            assert "shard.relay" in names
            # every queried process answered or is named in sources
            assert any(s["proc"] == "router" for s in tree["sources"])
        finally:
            await harness.stop()

    async def test_reshard_wave_is_zero_error(self):
        harness = slo.SLOHarness(
            members=2, seed=22, probe_interval=0.02,
            session_timeout_ms=800, shards=2,
        )
        await harness.start()
        try:
            await harness.settle(0.2)
            await harness.run_scenario("reshard-wave", hold_s=0.1)
            await harness.settle(0.2)
            report = harness.report(trace_name="unit")
            entry = report["faults"]["reshard-wave"]
            assert entry["injected"] == 1
            # zero-downtime by construction (the scenario raises on any
            # slice error): never detected as an outage
            assert entry["detected"] == 0
            assert report["shards"]["slice_errors"] == 0
            assert report["shards"]["reshards"] == 2  # up and back down
        finally:
            await harness.stop()

    async def test_shard_scenarios_need_a_sharded_tier(self):
        harness = slo.SLOHarness(members=2, seed=23)
        await harness.start()
        try:
            with pytest.raises(ValueError):
                await harness.run_scenario("shard-kill")
            with pytest.raises(ValueError):
                await harness.run_scenario("reshard-wave")
        finally:
            await harness.stop()

    async def test_repair_disabled_withholds_the_respawn(self):
        harness = slo.SLOHarness(
            members=2, seed=24, probe_interval=0.02,
            session_timeout_ms=800, shards=2, repair=False,
        )
        await harness.start()
        try:
            assert harness.router.respawn_enabled is False
            await harness.settle(0.2)
            await harness.run_scenario("shard-kill", kills=1)
            await harness.settle(0.5)
            report = harness.report(trace_name="unit")
            # the slice stays dark: errors keep accumulating and no
            # respawn ever lands
            assert report["shards"]["respawns"] == 0
            assert report["shards"]["slice_errors"] > 0
            assert report["availability"] < 1.0
        finally:
            await harness.stop()
