"""Health checker unit tests.

Rebuild of reference test/health.test.js (the reference's only hermetic
tests) — same real-shell-command strategy: ``true``, ``false``, ``sleep``,
``echo``.  Adds coverage for the behaviors the reference never tested
(SURVEY.md §4): stdoutMatch.invert, window expiry, recovery clearing the
down state.
"""

import asyncio
import time
import tracemalloc

import pytest

from registrar_tpu.health import (
    DEFAULT_INTERVAL_S,
    DEFAULT_PERIOD_S,
    DEFAULT_THRESHOLD,
    DEFAULT_TIMEOUT_S,
    DownError,
    HealthCheck,
    HealthCheckError,
    create_health_check,
)


class TestDefaults:
    def test_reference_timing_constants(self):
        # BASELINE.md: 60s interval, 1s timeout, threshold 5, 300s window
        assert DEFAULT_INTERVAL_S == 60.0
        assert DEFAULT_TIMEOUT_S == 1.0
        assert DEFAULT_THRESHOLD == 5
        assert DEFAULT_PERIOD_S == 300.0
        hc = HealthCheck(command="true")
        assert (hc.interval, hc.timeout, hc.threshold, hc.period) == (
            60.0, 1.0, 5, 300.0,
        )

    def test_camelcase_config_keys(self):
        hc = create_health_check(
            **{
                "command": "true",
                "ignoreExitStatus": True,
                "stdoutMatch": {"pattern": "x", "invert": True},
            }
        )
        assert hc.ignore_exit_status is True
        assert hc._invert is True

    @pytest.mark.parametrize(
        "bad",
        [
            {"command": ""},
            {"command": "true", "interval": 0},
            {"command": "true", "threshold": 0},
            {"command": "true", "timeout": -1},
            {"command": "true", "stdout_match": {"pattern": "x", "flags": "q"}},
        ],
    )
    def test_validation(self, bad):
        with pytest.raises(ValueError):
            create_health_check(**bad)


class TestSingleChecks:
    async def test_ok(self):
        # reference test/health.test.js:29-52
        hc = HealthCheck(command="true")
        rec = await hc.check_once()
        assert rec == {"type": "ok", "command": "true"}

    async def test_exit_failure(self):
        # reference test/health.test.js:83-112
        hc = HealthCheck(command="false")
        rec = await hc.check_once()
        assert rec["type"] == "fail"
        assert rec["failures"] == 1
        assert rec["isDown"] is False
        assert rec["threshold"] == 5
        assert isinstance(rec["err"], HealthCheckError)
        assert rec["err"].code == 1

    async def test_ignore_exit_status(self):
        # reference test/health.test.js:56-80
        hc = HealthCheck(command="false", ignore_exit_status=True)
        rec = await hc.check_once()
        assert rec["type"] == "ok"

    async def test_timeout_kills_command(self):
        # reference test/health.test.js:115-145 (sleep 2 vs 1s timeout)
        hc = HealthCheck(command="sleep 2", timeout=0.2)
        rec = await hc.check_once()
        assert rec["type"] == "fail"
        assert "timed out" in str(rec["err"])

    async def test_stdout_match_ok(self):
        hc = HealthCheck(
            command="echo hello", stdout_match={"pattern": "^hel", "flags": "m"}
        )
        rec = await hc.check_once()
        assert rec["type"] == "ok"

    async def test_stdout_match_failure(self):
        # reference test/health.test.js:148-180
        hc = HealthCheck(command="echo nope", stdout_match={"pattern": "hello"})
        rec = await hc.check_once()
        assert rec["type"] == "fail"
        assert rec["err"].code == -1

    async def test_stdout_match_invert(self):
        # invert is validated but unimplemented in the reference
        # (lib/health.js:32-33) — implemented here
        hc = HealthCheck(
            command="echo ERROR: kaboom",
            stdout_match={"pattern": "ERROR", "invert": True},
        )
        rec = await hc.check_once()
        assert rec["type"] == "fail"

        hc2 = HealthCheck(
            command="echo all fine",
            stdout_match={"pattern": "ERROR", "invert": True},
        )
        assert (await hc2.check_once())["type"] == "ok"

    async def test_case_insensitive_flag(self):
        hc = HealthCheck(
            command="echo HELLO", stdout_match={"pattern": "hello", "flags": "i"}
        )
        assert (await hc.check_once())["type"] == "ok"

    async def test_unspawnable_command_is_failure(self):
        hc = HealthCheck(command="/nonexistent/binary/xyz")
        rec = await hc.check_once()
        assert rec["type"] == "fail"

    async def test_grandchild_holding_pipes_cannot_wedge_the_check(self):
        # A backgrounded grandchild inherits the stdout/stderr pipes and
        # outlives the SIGTERM/SIGKILL aimed at the shell, so the pipes
        # never reach EOF.  The drain must be bounded — the check reports
        # the timeout and health checking continues, instead of blocking
        # until the grandchild dies.
        hc = HealthCheck(command="sleep 30 & sleep 30", timeout=0.2)
        t0 = time.monotonic()
        rec = await hc.check_once()
        assert rec["type"] == "fail"
        assert "timed out" in str(rec["err"])
        assert time.monotonic() - t0 < 5


class TestSingleCheckEdges:
    async def test_stdout_match_s_flag_spans_newlines(self):
        # JS "s" (dotAll) maps to re.DOTALL; without it the same pattern
        # must fail across a newline.
        dotall = HealthCheck(
            command="printf 'a\\nb'", stdout_match={"pattern": "a.b", "flags": "s"}
        )
        assert (await dotall.check_once())["type"] == "ok"
        plain = HealthCheck(
            command="printf 'a\\nb'", stdout_match={"pattern": "a.b"}
        )
        assert (await plain.check_once())["type"] == "fail"

    async def test_stateful_js_flags_are_ignored(self):
        # "g"/"u"/"y" have no Python equivalent and must be tolerated
        # (real configs carry them; the reference passes them to RegExp).
        hc = HealthCheck(
            command="echo hello", stdout_match={"pattern": "hell", "flags": "guy"}
        )
        assert (await hc.check_once())["type"] == "ok"

    async def test_spawn_failure_is_a_fail_record(self, monkeypatch):
        # OSError from process creation (fd exhaustion, fork failure)
        # must surface as a normal fail record, not an exception.
        import registrar_tpu.health as health_mod

        async def boom(*a, **kw):
            raise OSError("out of file descriptors")

        monkeypatch.setattr(
            health_mod.asyncio, "create_subprocess_shell", boom
        )
        hc = HealthCheck(command="true")
        rec = await hc.check_once()
        assert rec["type"] == "fail"
        assert "failed to spawn" in str(rec["err"])

    async def test_cancel_mid_check_is_prompt(self):
        # stop() mid-check: the CancelledError must propagate PROMPTLY.
        # A naive proc.wait() blocks until the stdout/stderr pipes see
        # EOF, so a pipe-holder (the killed shell's own child) wedged
        # the stop for the child's whole 30 s lifetime before the
        # bounded wait.  The direct child is SIGKILLed; a grandchild
        # orphaned by the dying shell can survive — the same semantics
        # as the reference's child_process.exec kill, which also signals
        # only the shell (lib/health.js:45-52).
        import subprocess
        import time

        # A duration unique to this test, so the cleanup pkill cannot
        # match anything else on a shared machine.
        marker = "sleep 30.731897"
        hc = HealthCheck(command=marker, timeout=60)
        task = asyncio.ensure_future(hc.check_once())
        await asyncio.sleep(0.3)  # let the child spawn
        t0 = time.monotonic()
        task.cancel()
        with pytest.raises(asyncio.CancelledError):
            await task
        assert time.monotonic() - t0 < 5, "cancellation was wedged"
        # good citizenship: reap any orphaned sleep before the next test
        await asyncio.to_thread(
            subprocess.run, ["pkill", "-f", marker], capture_output=True
        )
        # one tick for the reaped transport's close callbacks to land
        await asyncio.sleep(0.05)


class TestThreshold:
    async def test_threshold_crossing_sets_down(self):
        # reference test/health.test.js:183-225 (interval 5ms, threshold 3)
        hc = HealthCheck(command="false", threshold=3)
        records = [await hc.check_once() for _ in range(4)]
        assert [r["isDown"] for r in records] == [False, False, True, True]
        crossing = records[2]
        assert isinstance(crossing["err"], DownError)
        assert len(crossing["err"].errors) == 3
        assert hc.is_down

    async def test_window_expiry_prunes_old_failures(self):
        # failures separated by more than `period` never accumulate
        hc = HealthCheck(command="false", threshold=2, period=0.05)
        r1 = await hc.check_once()
        await asyncio.sleep(0.08)
        r2 = await hc.check_once()
        assert r1["failures"] == 1
        assert r2["failures"] == 1  # the first aged out of the window
        assert not hc.is_down

    async def test_recovery_clears_down_and_window(self):
        # fix over the reference: ok while down resets everything
        hc = HealthCheck(command="false", threshold=2)
        await hc.check_once()
        await hc.check_once()
        assert hc.is_down
        hc.command = "true"
        assert (await hc.check_once())["type"] == "ok"
        assert not hc.is_down
        hc.command = "false"
        rec = await hc.check_once()
        assert rec["failures"] == 1  # fresh window, not instant re-down
        assert rec["isDown"] is False


class TestOutputCap:
    """The 1 MiB cap is enforced *while streaming* (reference
    lib/health.js:45-52 exec maxBuffer): the child is killed the moment
    its output crosses the cap, and the daemon never retains more than
    the cap in memory — a runaway writer cannot OOM the sidecar."""

    async def test_runaway_writer_killed_at_cap(self):
        # 16 MiB burst then a long sleep: without the streaming kill the
        # check would buffer the burst and sit out the sleep until the
        # timeout; with it, the SIGTERM lands as the cap is crossed and
        # the sleep never runs.
        hc = HealthCheck(
            command="head -c 16777216 /dev/zero; sleep 5", timeout=10
        )
        tracemalloc.start()
        t0 = time.monotonic()
        rec = await hc.check_once()
        elapsed = time.monotonic() - t0
        _, peak = tracemalloc.get_traced_memory()
        tracemalloc.stop()
        assert rec["type"] == "fail"
        assert "exceeded output limit" in str(rec["err"])
        assert elapsed < 4, "child was not killed at the cap"
        # Bounded memory: the 16 MiB burst must not be accumulated —
        # only up to the 1 MiB cap (plus small read buffers) is retained.
        assert peak < 4 * 1024 * 1024, f"peak {peak} bytes: output buffered"

    async def test_stderr_counts_against_cap(self):
        hc = HealthCheck(
            command="head -c 16777216 /dev/zero 1>&2; sleep 5", timeout=10
        )
        t0 = time.monotonic()
        rec = await hc.check_once()
        assert rec["type"] == "fail"
        assert "exceeded output limit" in str(rec["err"])
        assert time.monotonic() - t0 < 4

    async def test_output_at_exactly_cap_passes(self):
        # Boundary parity with the pre-streaming behavior: the check
        # fails only when output *exceeds* the cap.
        hc = HealthCheck(command="head -c 1048576 /dev/zero", timeout=10)
        assert (await hc.check_once())["type"] == "ok"

    async def test_capped_stdout_still_matched(self):
        # stdoutMatch sees the retained prefix even on a capped run —
        # but the cap failure wins, like Node's maxBuffer error.
        hc = HealthCheck(
            command="echo hello; head -c 2097152 /dev/zero",
            timeout=10,
            stdout_match={"pattern": "hello"},
        )
        rec = await hc.check_once()
        assert rec["type"] == "fail"
        assert "exceeded output limit" in str(rec["err"])


class TestLoopCrashRestart:
    """An unexpected exception in the check loop must never silently end
    health checking while the host stays registered (round-4 verdict):
    the crash counts as a failed check and the loop restarts with
    backoff."""

    async def test_crash_restarts_and_counts_as_failure(self):
        hc = HealthCheck(
            command="true", interval=0.01, threshold=2, period=10
        )
        hc.CRASH_BACKOFF_INITIAL_S = 0.01
        calls = {"n": 0}
        real_check_once = hc.check_once

        async def flaky_check_once():
            calls["n"] += 1
            if calls["n"] <= 2:
                raise RuntimeError("injected crash")
            return await real_check_once()

        hc.check_once = flaky_check_once
        records, errors = [], []
        hc.on("data", records.append)
        hc.on("error", errors.append)
        hc.start()
        try:
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                if any(r["type"] == "ok" for r in records):
                    break
                await asyncio.sleep(0.01)
        finally:
            hc.stop()
        # Both crashes surfaced and counted toward the threshold...
        assert len(errors) == 2
        fails = [r for r in records if r["type"] == "fail"]
        assert len(fails) == 2
        assert [f["isDown"] for f in fails] == [False, True]
        assert all("crashed" in str(f["err"]) for f in fails)
        # ...and checking resumed: real checks ran again after the
        # crashes and recovery cleared the down state.
        assert any(r["type"] == "ok" for r in records)
        assert not hc.is_down


class TestLoop:
    async def test_start_stop_stream(self):
        hc = HealthCheck(command="true", interval=0.02)
        seen = []
        ended = asyncio.Event()
        hc.on("data", seen.append)
        hc.on("end", lambda *a: ended.set())
        hc.start()
        await asyncio.sleep(0.08)
        hc.stop()
        await asyncio.wait_for(ended.wait(), 1)
        assert len(seen) >= 2
        assert all(r["type"] == "ok" for r in seen)
        assert not hc.running

    async def test_start_idempotent(self):
        hc = HealthCheck(command="true", interval=0.02)
        hc.start()
        task = hc._task
        hc.start()
        assert hc._task is task
        hc.stop()


class TestProcessGroupKill:
    """ISSUE 5 satellite: timeout kills reach the whole process GROUP.

    Pre-fix, only the shell got terminate()/kill(): a grandchild the
    shell spawned survived every escalation (and held the output pipes
    open past the reap) — a health command leak per timeout, forever.
    """

    async def test_timeout_reaps_trap_ignoring_grandchild(self, tmp_path):
        import os
        import sys
        import time as time_mod

        pidfile = tmp_path / "grandchild.pid"
        script = (
            "import os, signal, time; "
            "signal.signal(signal.SIGTERM, signal.SIG_IGN); "
            f"open({str(pidfile)!r}, 'w').write(str(os.getpid())); "
            "time.sleep(30)"
        )
        # background + wait: the python process is a GRANDchild of the
        # health shell (same process group), not the shell itself
        command = f'{sys.executable} -c "{script}" & wait'
        check = HealthCheck(command=command, timeout=0.5, interval=60)
        record = await check.check_once()
        assert record["type"] == "fail"
        assert "timed out" in str(record["err"])
        assert pidfile.exists(), "grandchild never started"
        pid = int(pidfile.read_text())

        deadline = time_mod.monotonic() + 5
        while time_mod.monotonic() < deadline:
            try:
                os.kill(pid, 0)
            except ProcessLookupError:
                break  # reaped: the group SIGKILL reached it
            await asyncio.sleep(0.05)
        else:
            try:
                os.kill(pid, 9)  # do not leak it out of the test either
            except ProcessLookupError:
                pass
            raise AssertionError(
                "SIGTERM-ignoring grandchild survived the timeout kill"
            )

    async def test_output_cap_kill_also_hits_the_group(self, tmp_path):
        import os
        import sys
        import time as time_mod

        pidfile = tmp_path / "grandchild.pid"
        script = (
            "import os, signal, sys, time; "
            "signal.signal(signal.SIGTERM, signal.SIG_IGN); "
            f"open({str(pidfile)!r}, 'w').write(str(os.getpid())); "
            "sys.stdout.write('x' * (2 * 1024 * 1024)); "
            "sys.stdout.flush(); time.sleep(30)"
        )
        command = f'{sys.executable} -c "{script}" & wait'
        check = HealthCheck(command=command, timeout=5.0, interval=60)
        record = await check.check_once()
        assert record["type"] == "fail"
        pid = int(pidfile.read_text())
        deadline = time_mod.monotonic() + 8
        while time_mod.monotonic() < deadline:
            try:
                os.kill(pid, 0)
            except ProcessLookupError:
                break
            await asyncio.sleep(0.05)
        else:
            try:
                os.kill(pid, 9)
            except ProcessLookupError:
                pass
            raise AssertionError("runaway grandchild survived the cap kill")
