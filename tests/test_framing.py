"""Unit tests for the shared length-prefixed frame buffering.

`registrar_tpu/zk/framing.py` is used by both the client's read loop and
the server's request loop; these tests pin the carving semantics the two
hot paths rely on (burst carving, split frames, corrupt lengths, the 4lw
header peek, and the reply-batching `pending()` probe).
"""

import asyncio

import pytest

from registrar_tpu.zk.framing import MAX_FRAME, FrameReader


def _frame(payload: bytes) -> bytes:
    return len(payload).to_bytes(4, "big") + payload


class _FakeReader:
    """StreamReader stand-in serving a scripted sequence of read() chunks."""

    def __init__(self, chunks):
        self._chunks = list(chunks)

    async def read(self, _n):
        if not self._chunks:
            return b""  # EOF
        chunk = self._chunks.pop(0)
        if isinstance(chunk, Exception):
            raise chunk
        return chunk


def run(coro):
    return asyncio.run(coro)


class TestCarve:
    def test_carves_every_complete_frame_in_a_burst(self):
        burst = _frame(b"one") + _frame(b"two") + _frame(b"")
        fr = FrameReader(_FakeReader([burst]))

        async def go():
            assert await fr.fill()
            return fr.carve()

        assert run(go()) == [b"one", b"two", b""]

    def test_frame_split_across_fills(self):
        whole = _frame(b"split-payload")
        fr = FrameReader(_FakeReader([whole[:5], whole[5:]]))

        async def go():
            assert await fr.fill()
            first = fr.carve()
            assert await fr.fill()
            return first, fr.carve()

        first, second = run(go())
        assert first == []
        assert second == [b"split-payload"]

    def test_partial_trailing_frame_stays_buffered(self):
        tail = _frame(b"whole") + _frame(b"partial")[:6]
        fr = FrameReader(_FakeReader([tail]))

        async def go():
            assert await fr.fill()
            return fr.carve(), fr.pending()

        carved, pending = run(go())
        assert carved == [b"whole"]
        assert pending is False  # remainder is incomplete

    def test_negative_length_raises_connection_error(self):
        fr = FrameReader(_FakeReader([(-1).to_bytes(4, "big", signed=True)]))

        async def go():
            assert await fr.fill()
            fr.carve()

        with pytest.raises(ConnectionError):
            run(go())

    def test_oversized_length_raises_connection_error(self):
        fr = FrameReader(
            _FakeReader([(MAX_FRAME + 1).to_bytes(4, "big", signed=True)])
        )

        async def go():
            assert await fr.fill()
            fr.carve()

        with pytest.raises(ConnectionError):
            run(go())


class TestPending:
    def test_pending_only_when_complete(self):
        whole = _frame(b"abc")
        fr = FrameReader(_FakeReader([whole[:4], whole[4:]]))

        async def go():
            assert await fr.fill()
            before = fr.pending()
            assert await fr.fill()
            return before, fr.pending()

        before, after = run(go())
        assert before is False
        assert after is True

    def test_pending_false_on_empty(self):
        assert FrameReader(_FakeReader([])).pending() is False


class TestFill:
    def test_fill_drains_buffered_burst_past_read_size(self):
        # A burst larger than the 64 KB read size that is ALREADY
        # buffered in the StreamReader must land in one fill(), so the
        # reply batchers see one burst, not one 64 KB chunk at a time
        # (ADVICE r5: pending() used to declare the burst exhausted at
        # every read-size boundary, costing a flush+drain per chunk).
        async def go():
            reader = asyncio.StreamReader()
            payload = b"x" * 40000
            reader.feed_data(b"".join(_frame(payload) for _ in range(4)))
            reader.feed_eof()
            fr = FrameReader(reader)
            assert await fr.fill()
            return fr.carve()

        frames = run(go())
        assert len(frames) == 4  # ~160 KB ingested by a single fill()

    def test_eof_returns_false(self):
        fr = FrameReader(_FakeReader([]))
        assert run(fr.fill()) is False

    def test_connection_error_returns_false(self):
        fr = FrameReader(_FakeReader([ConnectionResetError()]))
        assert run(fr.fill()) is False


class TestZeroCopyGrowth:
    """ISSUE 11: the chunk-deque buffer — zero-copy carving for
    within-chunk frames, bounded retention, no re-copy on large bursts
    (the old bytearray memmove-compacted the whole remaining burst on
    every fill and copied each frame out of it)."""

    def test_within_chunk_frames_are_views_into_the_receive_chunk(self):
        chunk = _frame(b"one") + _frame(b"two")
        fr = FrameReader(_FakeReader([chunk]))

        async def go():
            assert await fr.fill()
            return fr.carve()

        one, two = run(go())
        # Zero-copy: both payloads alias the original receive chunk.
        assert isinstance(one, memoryview) and one.obj is chunk
        assert isinstance(two, memoryview) and two.obj is chunk
        assert one == b"one" and two == b"two"

    def test_whole_chunk_frame_is_the_chunk_tail_itself(self):
        # A frame whose payload ends exactly at the chunk boundary
        # consumes the chunk; the final take may hand back the chunk
        # (or a view of it) but never a copy.
        payload = b"x" * 1000
        chunk = _frame(payload)
        fr = FrameReader(_FakeReader([chunk]))

        async def go():
            assert await fr.fill()
            return fr.carve()

        (got,) = run(go())
        assert isinstance(got, memoryview) and got.obj is chunk

    def test_spanning_frame_joins_exactly_once(self):
        whole = _frame(b"A" * 100)
        fr = FrameReader(_FakeReader([whole[:40], whole[40:]]))

        async def go():
            assert await fr.fill()
            first = fr.carve()
            assert await fr.fill()
            return first, fr.carve()

        first, second = run(go())
        assert first == []
        assert second == [b"A" * 100]
        assert type(second[0]) is bytes  # joined copy, boundary case

    def test_burst_consumption_drops_chunks_as_it_goes(self):
        # The 10k-znode-sweep regression (PR-1 burst test's big sibling):
        # a >64 KB burst arriving as many chunks must not accumulate —
        # consumed chunks are released at carve time, so the buffered
        # residue after carving a huge burst is zero, not a re-copied
        # prefix.
        n_frames = 2000
        burst = b"".join(_frame(b"p" * 84) for _ in range(n_frames))
        chunk_size = 65536
        chunks = [
            burst[i : i + chunk_size]
            for i in range(0, len(burst), chunk_size)
        ]

        async def go():
            reader = asyncio.StreamReader()
            for c in chunks:
                reader.feed_data(c)
            reader.feed_eof()
            fr = FrameReader(reader)
            assert await fr.fill()
            frames = fr.carve()
            return frames, len(fr._chunks), fr._size

        frames, residual_chunks, residual_bytes = run(go())
        assert len(frames) == n_frames
        assert all(f == b"p" * 84 for f in frames)
        # nothing retained once every frame is carved
        assert residual_chunks == 0 and residual_bytes == 0

    def test_max_frame_boundary_accepted(self):
        payload = b"z" * MAX_FRAME
        fr = FrameReader(_FakeReader([_frame(payload)]))

        async def go():
            while not fr.pending():
                assert await fr.fill()
            return fr.carve()

        (got,) = run(go())
        assert len(got) == MAX_FRAME

    def test_frame_nowait_fast_path(self):
        fr = FrameReader(_FakeReader([_frame(b"abc") + _frame(b"de")[:4]]))

        async def go():
            assert fr.frame_nowait() is None  # nothing buffered yet
            assert await fr.fill()
            first = fr.frame_nowait()
            incomplete = fr.frame_nowait()
            return first, incomplete

        first, incomplete = run(go())
        assert first == b"abc"
        assert incomplete is None  # partial trailing frame: await path

    def test_frame_nowait_defers_corrupt_length_to_frame(self):
        fr = FrameReader(
            _FakeReader([(-3).to_bytes(4, "big", signed=True) + b"xx"])
        )

        async def go():
            assert await fr.fill()
            assert fr.frame_nowait() is None  # deferred, not raised
            return await fr.frame()

        assert run(go()) is None  # the awaited path owns the verdict


class TestHandshakeHelpers:
    def test_read4_then_frame_with_header(self):
        # The server peeks 4 bytes to detect 4lw commands, then hands the
        # peeked length back to frame() for the ConnectRequest.
        payload = b"connect-record"
        fr = FrameReader(_FakeReader([_frame(payload)]))

        async def go():
            hdr = await fr.read4()
            return hdr, await fr.frame(header=hdr)

        hdr, got = run(go())
        assert hdr == len(payload).to_bytes(4, "big")
        assert got == payload

    def test_read4_sees_ascii_command_bytes(self):
        fr = FrameReader(_FakeReader([b"ruok"]))
        assert run(fr.read4()) == b"ruok"

    def test_frame_returns_none_on_bad_length(self):
        fr = FrameReader(
            _FakeReader([(-2).to_bytes(4, "big", signed=True) + b"xx"])
        )
        assert run(fr.frame()) is None

    def test_frame_returns_none_on_eof_mid_payload(self):
        fr = FrameReader(_FakeReader([_frame(b"full-payload")[:7]]))
        assert run(fr.frame()) is None

    def test_sequential_frames(self):
        fr = FrameReader(_FakeReader([_frame(b"a") + _frame(b"bb")]))

        async def go():
            return await fr.frame(), await fr.frame(), await fr.frame()

        assert run(go()) == (b"a", b"bb", None)


class TestReplyEventOrdering:
    """The server-side batching invariant: an event emitted while replies
    sit queued must drain those replies first — a watch notification may
    never overtake the reply to an earlier request on the same
    connection (real ZooKeeper's single outgoing queue gives the same
    guarantee)."""

    def test_send_event_drains_queued_replies_first(self):
        from types import SimpleNamespace

        from registrar_tpu.testing.server import _Connection
        from registrar_tpu.zk.protocol import EventType

        class _FakeWriter:
            def __init__(self):
                self.data = bytearray()

            def write(self, b):
                self.data += b

            async def drain(self):
                pass

            def get_extra_info(self, _name):
                return ("127.0.0.1", 1)

        async def go():
            writer = _FakeWriter()
            server = SimpleNamespace(packets_sent=0)
            conn = _Connection(server, reader=None, writer=writer)
            conn.queue(b"reply-1")
            conn.queue(b"reply-2")
            await conn.send_event(EventType.NODE_DATA_CHANGED, "/watched")
            return bytes(writer.data), server.packets_sent

        data, sent = run(go())
        # Carve the concatenated frames and check the order on the wire.
        frames = []
        pos = 0
        while pos < len(data):
            length = int.from_bytes(data[pos:pos + 4], "big")
            frames.append(data[pos + 4:pos + 4 + length])
            pos += 4 + length
        assert frames[0] == b"reply-1"
        assert frames[1] == b"reply-2"
        # Frame 3 is the notification: ReplyHeader xid -1 (0xffffffff).
        assert len(frames) == 3
        assert frames[2][:4] == (-1).to_bytes(4, "big", signed=True)
        assert b"/watched" in frames[2]
        assert sent == 3
