"""Unit tests for the EventEmitter backbone.

`registrar_tpu/events.py` carries every daemon-facing signal (the 7-event
orchestrator surface, client connect/close/watch events), so its full
API — including off(), listener_count(), async listener dispatch, and
the raise guards — is pinned here.
"""

import asyncio
import logging

from registrar_tpu.events import EventEmitter


class TestRegistry:
    def test_on_returns_listener_and_emit_counts(self):
        ee = EventEmitter()
        seen = []
        listener = ee.on("ev", lambda *a: seen.append(a))
        assert callable(listener)
        assert ee.emit("ev", 1, 2) == 1
        assert seen == [(1, 2)]

    def test_once_fires_exactly_once(self):
        ee = EventEmitter()
        seen = []
        ee.once("ev", lambda: seen.append("x"))
        assert ee.emit("ev") == 1
        assert ee.emit("ev") == 0
        assert seen == ["x"]

    def test_off_removes_from_both_registries(self):
        ee = EventEmitter()

        def listener():
            raise AssertionError("removed listener must not fire")

        ee.on("ev", listener)
        ee.off("ev", listener)
        assert ee.emit("ev") == 0

        ee.once("ev", listener)
        ee.off("ev", listener)
        assert ee.emit("ev") == 0

    def test_off_unknown_listener_is_noop(self):
        EventEmitter().off("ev", lambda: None)  # must not raise

    def test_listener_count_spans_both_registries(self):
        ee = EventEmitter()
        ee.on("ev", lambda: None)
        ee.once("ev", lambda: None)
        assert ee.listener_count("ev") == 2
        assert ee.listener_count("other") == 0


class TestDispatchGuards:
    def test_raising_listener_does_not_break_the_rest(self, caplog):
        ee = EventEmitter()
        seen = []

        def bad():
            raise RuntimeError("boom")

        ee.on("ev", bad)
        ee.on("ev", lambda: seen.append("ok"))
        with caplog.at_level(logging.ERROR, logger="registrar_tpu.events"):
            assert ee.emit("ev") == 2
        assert seen == ["ok"]
        assert any("listener for" in r.message for r in caplog.records)

    async def test_async_listener_runs_as_task(self):
        ee = EventEmitter()
        done = asyncio.Event()

        async def listener(val):
            assert val == 42
            done.set()

        ee.on("ev", listener)
        ee.emit("ev", 42)
        await asyncio.wait_for(done.wait(), timeout=5)

    async def test_async_listener_raise_is_guarded(self, caplog):
        ee = EventEmitter()

        async def bad():
            raise RuntimeError("async boom")

        ee.on("ev", bad)
        with caplog.at_level(logging.ERROR, logger="registrar_tpu.events"):
            ee.emit("ev")
            await asyncio.sleep(0.05)  # let the guard task run
        assert any("async listener" in r.message for r in caplog.records)

    async def test_wait_for_returns_emitted_args(self):
        ee = EventEmitter()
        loop = asyncio.get_running_loop()
        loop.call_soon(lambda: ee.emit("ev", "a", 3))
        assert await ee.wait_for("ev", timeout=5) == ("a", 3)
