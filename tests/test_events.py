"""Unit tests for the EventEmitter backbone.

`registrar_tpu/events.py` carries every daemon-facing signal (the 7-event
orchestrator surface, client connect/close/watch events), so its full
API — including off(), listener_count(), async listener dispatch, and
the raise guards — is pinned here.
"""

import asyncio
import logging

import pytest

from registrar_tpu.events import EventEmitter


class TestRegistry:
    def test_on_returns_listener_and_emit_counts(self):
        ee = EventEmitter()
        seen = []
        listener = ee.on("ev", lambda *a: seen.append(a))
        assert callable(listener)
        assert ee.emit("ev", 1, 2) == 1
        assert seen == [(1, 2)]

    def test_once_fires_exactly_once(self):
        ee = EventEmitter()
        seen = []
        ee.once("ev", lambda: seen.append("x"))
        assert ee.emit("ev") == 1
        assert ee.emit("ev") == 0
        assert seen == ["x"]

    def test_off_removes_from_both_registries(self):
        ee = EventEmitter()

        def listener():
            raise AssertionError("removed listener must not fire")

        ee.on("ev", listener)
        ee.off("ev", listener)
        assert ee.emit("ev") == 0

        ee.once("ev", listener)
        ee.off("ev", listener)
        assert ee.emit("ev") == 0

    def test_off_unknown_listener_is_noop(self):
        EventEmitter().off("ev", lambda: None)  # must not raise

    def test_listener_count_spans_both_registries(self):
        ee = EventEmitter()
        ee.on("ev", lambda: None)
        ee.once("ev", lambda: None)
        assert ee.listener_count("ev") == 2
        assert ee.listener_count("other") == 0


class TestDispatchGuards:
    def test_raising_listener_does_not_break_the_rest(self, caplog):
        ee = EventEmitter()
        seen = []

        def bad():
            raise RuntimeError("boom")

        ee.on("ev", bad)
        ee.on("ev", lambda: seen.append("ok"))
        with caplog.at_level(logging.ERROR, logger="registrar_tpu.events"):
            assert ee.emit("ev") == 2
        assert seen == ["ok"]
        assert any("listener for" in r.message for r in caplog.records)

    async def test_async_listener_runs_as_task(self):
        ee = EventEmitter()
        done = asyncio.Event()

        async def listener(val):
            assert val == 42
            done.set()

        ee.on("ev", listener)
        ee.emit("ev", 42)
        await asyncio.wait_for(done.wait(), timeout=5)

    async def test_async_listener_raise_is_guarded(self, caplog):
        ee = EventEmitter()

        async def bad():
            raise RuntimeError("async boom")

        ee.on("ev", bad)
        with caplog.at_level(logging.ERROR, logger="registrar_tpu.events"):
            ee.emit("ev")
            await asyncio.sleep(0.05)  # let the guard task run
        assert any("async listener" in r.message for r in caplog.records)

    async def test_wait_for_returns_emitted_args(self):
        ee = EventEmitter()
        loop = asyncio.get_running_loop()
        loop.call_soon(lambda: ee.emit("ev", "a", 3))
        assert await ee.wait_for("ev", timeout=5) == ("a", 3)


class TestSpawnOwned:
    def test_closed_loop_tasks_are_evicted(self):
        # A loop closed without draining its tasks strands them in the
        # module-global dispatch registry (their done-callbacks can
        # never fire); the next spawn from a NEW loop must evict them so
        # the set cannot grow forever in a process that cycles loops.
        from registrar_tpu import events

        registry = events._DISPATCH_TASKS
        saved = set(registry)
        registry.clear()
        try:

            async def forever():
                await asyncio.Event().wait()

            async def strand():
                events.spawn_owned(forever(), registry)

            loop = asyncio.new_event_loop()
            try:
                loop.run_until_complete(strand())
            finally:
                loop.close()  # deliberately without cancelling
            assert len(registry) == 1  # stranded

            async def noop():
                pass

            async def spawn_and_drain():
                task = events.spawn_owned(noop(), registry)
                await task
                await asyncio.sleep(0)  # let the done-callback run

            asyncio.run(spawn_and_drain())
            assert not registry  # stranded evicted, new task discarded
        finally:
            registry.update(saved)

    def test_spawn_without_running_loop_raises_cleanly(self):
        # Off-loop callers must get the RuntimeError (as before the
        # refactor), not an orphaned 'never awaited' coroutine warning.
        import warnings

        from registrar_tpu.events import spawn_owned

        async def noop():
            pass

        with warnings.catch_warnings():
            warnings.simplefilter("error", RuntimeWarning)
            with pytest.raises(RuntimeError):
                spawn_owned(noop(), set())

    def test_emit_without_loop_closes_listener_coroutine(self, caplog):
        # emit() off-loop follows its normal guard contract (the error
        # is logged, other listeners still run) — but the listener's
        # coroutine must be CLOSED, not leaked for garbage collection
        # to warn 'coroutine was never awaited' about.
        import gc
        import warnings

        ee = EventEmitter()

        async def listener():
            pass

        ee.on("ev", listener)
        with warnings.catch_warnings():
            warnings.simplefilter("error", RuntimeWarning)
            with caplog.at_level(
                logging.ERROR, logger="registrar_tpu.events"
            ):
                assert ee.emit("ev") == 1
            gc.collect()  # would raise RuntimeWarning on a leaked coro
        assert any("listener for" in r.message for r in caplog.records)
