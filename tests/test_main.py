"""Daemon mainline tests, including a full end-to-end subprocess run.

The e2e test is the rebuild's version of SURVEY.md §7's "minimum end-to-end
slice": start the in-process ZK server, run the *real* daemon process
against a coal-style config, verify the znode JSON byte-for-byte, then
kill the daemon and watch the ephemeral vanish on session expiry.
"""

import asyncio
import json
import os
import signal
import socket
import subprocess
import sys

import pytest

from registrar_tpu.main import parse_args
from registrar_tpu.testing.server import ZKServer
from registrar_tpu.zk.client import ZKClient

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestArgs:
    def test_file_required(self, capsys):
        with pytest.raises(SystemExit) as ei:
            parse_args([])
        assert ei.value.code == 2

    def test_verbose_count(self):
        args = parse_args(["-f", "x.json", "-v", "-v"])
        assert args.verbose == 2
        assert args.file == "x.json"

    def test_help_exits_zero(self):
        with pytest.raises(SystemExit) as ei:
            parse_args(["-h"])
        assert ei.value.code == 0


class TestCheckConfig:
    """-n/--check-config: validate-and-exit, no ZooKeeper involved."""

    def _run(self, tmp_path, payload):
        path = tmp_path / "cfg.json"
        path.write_text(payload)
        # Pin LOG_LEVEL: an ambient LOG_LEVEL=error in the caller's shell
        # would suppress the log lines these tests assert on.
        env = {**os.environ, "PYTHONPATH": REPO, "LOG_LEVEL": "info"}
        return subprocess.run(
            [sys.executable, "-m", "registrar_tpu", "-f", str(path), "-n"],
            cwd=REPO, capture_output=True, text=True, timeout=30,
            env=env,
        )

    def test_valid_config_exits_zero(self, tmp_path):
        out = self._run(tmp_path, json.dumps({
            "registration": {"domain": "a.b", "type": "host"},
            # unreachable ensemble: -n must not try to connect
            "zookeeper": {"servers": [{"host": "192.0.2.123", "port": 9}]},
        }))
        assert out.returncode == 0
        assert "configuration OK" in out.stdout

    def test_missing_file_exits_one_not_ex_config(self, tmp_path):
        # A file that is not there yet (config-agent racing the unit at
        # boot) is transient: exit 1 so Restart=always retries, NOT 78
        # which RestartPreventExitStatus would make permanent.
        env = {**os.environ, "PYTHONPATH": REPO, "LOG_LEVEL": "info"}
        out = subprocess.run(
            [sys.executable, "-m", "registrar_tpu",
             "-f", str(tmp_path / "nope.json"), "-n"],
            cwd=REPO, capture_output=True, text=True, timeout=30, env=env,
        )
        assert out.returncode == 1
        assert "unable to read" in out.stdout

    def test_invalid_config_exits_ex_config(self, tmp_path):
        # 78 = EX_CONFIG: distinct from runtime exit(1) so systemd's
        # RestartPreventExitStatus can stop a bad config crash-looping.
        out = self._run(tmp_path, json.dumps({
            "registration": {"domain": "a.b", "type": "host"},
            "zookeeper": {"servers": []},
        }))
        assert out.returncode == 78
        assert "servers" in out.stdout  # the validation error is logged

    def test_unknown_keys_warn_but_validate(self, tmp_path):
        out = self._run(tmp_path, json.dumps({
            "registration": {"domain": "a.b", "type": "host"},
            "zookeeper": {"servers": [{"host": "h", "port": 1}]},
            "healthcheck": {"command": "true"},  # typo: lowercase c
        }))
        assert out.returncode == 0  # still valid (ignored, like the ref)
        assert "unrecognized top-level keys" in out.stdout
        assert "healthcheck" in out.stdout

    def test_unknown_key_warning_survives_quiet_log_level(self, tmp_path):
        # The warning must be emitted before the config's own logLevel
        # applies, or {"logLevel": "error"} would suppress it.
        out = self._run(tmp_path, json.dumps({
            "registration": {"domain": "a.b", "type": "host"},
            "zookeeper": {"servers": [{"host": "h", "port": 1}]},
            "logLevel": "error",
            "healthcheck": {"command": "true"},
        }))
        assert out.returncode == 0
        assert "unrecognized top-level keys" in out.stdout

    def test_invalid_registration_schema_exits_ex_config(self, tmp_path):
        # -n must apply the registration schema check register_plus runs
        # at startup, not just the config-file shape check.
        out = self._run(tmp_path, json.dumps({
            "registration": {"domain": "a.b"},  # missing required type
            "zookeeper": {"servers": [{"host": "h", "port": 1}]},
        }))
        assert out.returncode == 78
        assert "registration" in out.stdout


class TestEndToEnd:
    async def test_daemon_lifecycle(self, tmp_path):
        server = await ZKServer(max_session_timeout_ms=1000).start()
        observer = await ZKClient([server.address]).connect()
        try:
            config = {
                "registration": {
                    "domain": "e2e.test.registrar",
                    "type": "load_balancer",
                    "heartbeatInterval": 100,
                    "service": {
                        "type": "service",
                        "service": {
                            "srvce": "_http", "proto": "_tcp", "port": 80,
                        },
                    },
                },
                "adminIp": "10.66.66.66",
                "zookeeper": {
                    "servers": [
                        {"host": server.host, "port": server.port}
                    ],
                    "timeout": 800,
                },
                "logLevel": "debug",
            }
            cfg_path = tmp_path / "config.json"
            cfg_path.write_text(json.dumps(config))

            proc = subprocess.Popen(
                [sys.executable, "-m", "registrar_tpu", "-f", str(cfg_path)],
                cwd=REPO,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                env={**os.environ, "PYTHONPATH": REPO},
            )
            try:
                hostname = socket.gethostname()
                host_node = f"/registrar/test/e2e/{hostname}"
                svc_node = "/registrar/test/e2e"

                # up to ~10s for daemon start + 1s settle delay
                for _ in range(100):
                    if await observer.exists(host_node):
                        break
                    await asyncio.sleep(0.1)
                else:
                    raise AssertionError("host znode never appeared")

                data, st = await observer.get(host_node)
                assert st.ephemeral_owner != 0
                assert data == (
                    b'{"type":"load_balancer","address":"10.66.66.66",'
                    b'"load_balancer":{"address":"10.66.66.66","ports":[80]}}'
                )
                svc, svc_st = await observer.get(svc_node)
                assert svc_st.ephemeral_owner == 0
                assert json.loads(svc)["type"] == "service"

                # SIGKILL (the SMF ':kill' analog): no graceful cleanup;
                # the ephemeral must vanish via session expiry.
                proc.send_signal(signal.SIGKILL)
                proc.wait(timeout=10)
                for _ in range(100):
                    if not await observer.exists(host_node):
                        break
                    await asyncio.sleep(0.1)
                else:
                    raise AssertionError("ephemeral survived session expiry")
                # the persistent service record survives
                assert await observer.exists(svc_node) is not None
            finally:
                if proc.poll() is None:
                    proc.kill()
                out = proc.stdout.read().decode()
                # every log line must be valid bunyan JSON — except the
                # final one, which SIGKILL can truncate mid-write
                lines = out.splitlines()
                for i, line in enumerate(lines):
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        assert i == len(lines) - 1, (
                            f"corrupt non-final log line: {line!r}"
                        )
                        continue
                    assert rec["name"] == "registrar"
        finally:
            await observer.close()
            await server.stop()

    async def test_daemon_rides_through_zk_rolling_restart(self, tmp_path):
        # The ensemble restarts (state preserved, as a real quorum would):
        # the daemon must reattach its session and keep its registration
        # without restarting.
        server = await ZKServer(max_session_timeout_ms=30000).start()
        port = server.port
        config = {
            "registration": {"domain": "roll.e2e.registrar", "type": "host",
                              "heartbeatInterval": 200},
            "adminIp": "10.66.66.68",
            "zookeeper": {
                "servers": [{"host": "127.0.0.1", "port": port}],
                "timeout": 30000,
            },
        }
        cfg_path = tmp_path / "config.json"
        cfg_path.write_text(json.dumps(config))
        proc = subprocess.Popen(
            [sys.executable, "-m", "registrar_tpu", "-f", str(cfg_path)],
            cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            env={**os.environ, "PYTHONPATH": REPO},
        )
        try:
            hostname = socket.gethostname()
            node = f"/registrar/e2e/roll/{hostname}"
            observer = await ZKClient([("127.0.0.1", port)]).connect()
            try:
                for _ in range(100):
                    if await observer.exists(node):
                        break
                    await asyncio.sleep(0.1)
                else:
                    raise AssertionError("znode never appeared")
            finally:
                await observer.close()

            await server.stop()
            await asyncio.sleep(0.5)
            server = await ZKServer(port=port, snapshot=server).start()

            observer = await ZKClient([("127.0.0.1", port)]).connect()
            try:
                # the daemon's ephemeral must still be there (same session)
                # and the daemon must still be alive
                for _ in range(100):
                    st = await observer.exists(node)
                    if st is not None:
                        break
                    await asyncio.sleep(0.1)
                else:
                    raise AssertionError("ephemeral did not survive restart")
                assert st.ephemeral_owner != 0
                assert proc.poll() is None  # never crashed/restarted
            finally:
                await observer.close()
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)
            proc.stdout.close()
            await server.stop()

    async def test_daemon_survives_forced_session_expiry_in_process(
        self, tmp_path
    ):
        # ISSUE 3 acceptance, daemon-level: with surviveSessionExpiry +
        # reconcile.repair, a forced expiry must NOT exit(1) — the real
        # subprocess rides it out, re-registering under a fresh session.
        # (Reference parity when off is pinned by the SIGKILL e2e above:
        # expiry-driven ephemeral cleanup still works.)
        server = await ZKServer(max_session_timeout_ms=30000).start()
        observer = await ZKClient([server.address]).connect()
        config = {
            "registration": {"domain": "reborn.e2e.registrar", "type": "host",
                             "heartbeatInterval": 100},
            "adminIp": "10.66.66.69",
            "zookeeper": {
                "servers": [{"host": server.host, "port": server.port}],
                "timeout": 5000,
            },
            "surviveSessionExpiry": True,
            "reconcile": {"intervalSeconds": 0.2, "repair": True},
            "logLevel": "debug",
        }
        cfg_path = tmp_path / "config.json"
        cfg_path.write_text(json.dumps(config))
        proc = subprocess.Popen(
            [sys.executable, "-m", "registrar_tpu", "-f", str(cfg_path)],
            cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            env={**os.environ, "PYTHONPATH": REPO},
        )
        try:
            hostname = socket.gethostname()
            node = f"/registrar/e2e/reborn/{hostname}"
            for _ in range(100):
                st = await observer.exists(node)
                if st is not None:
                    break
                await asyncio.sleep(0.1)
            else:
                raise AssertionError("znode never appeared")
            old_owner = st.ephemeral_owner
            assert old_owner != 0

            # Force the daemon's session to expire: the ephemeral dies
            # with it, then must come back under a FRESH session with
            # the daemon process still alive.
            await server.expire_session(old_owner)
            for _ in range(100):
                st = await observer.exists(node)
                if st is not None and st.ephemeral_owner != old_owner:
                    break
                await asyncio.sleep(0.1)
            else:
                raise AssertionError(
                    "registration never reappeared under a fresh session"
                )
            assert st.ephemeral_owner != 0
            assert proc.poll() is None, "daemon exited on survivable expiry"
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)
            proc.stdout.close()
            await observer.close()
            await server.stop()

    async def test_daemon_exits_when_initial_registration_fails(self, tmp_path):
        # Reliability fix over the reference (which logs and idles broken,
        # lib/index.js:46-50): a failed initial registration exits(1) so
        # the supervisor restarts us.
        server = await ZKServer().start()
        try:
            config = {
                "registration": {"domain": "bad.test", "type": ""},  # invalid
                "adminIp": "10.0.0.1",
                "zookeeper": {
                    "servers": [{"host": server.host, "port": server.port}],
                },
            }
            cfg_path = tmp_path / "config.json"
            cfg_path.write_text(json.dumps(config))
            proc = subprocess.Popen(
                [sys.executable, "-m", "registrar_tpu", "-f", str(cfg_path)],
                cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                env={**os.environ, "PYTHONPATH": REPO},
            )
            try:
                rc = await asyncio.to_thread(proc.wait, 15)
                out = proc.stdout.read().decode()
                assert rc == 1, out
                assert "initial registration failed" in out
            finally:
                if proc.poll() is None:
                    proc.kill()
        finally:
            await server.stop()

    async def test_daemon_graceful_stop_drains_immediately(self, tmp_path):
        # SIGTERM: our addition — ephemerals deleted at once, not after
        # session timeout.
        server = await ZKServer(max_session_timeout_ms=30000).start()
        observer = await ZKClient([server.address]).connect()
        try:
            config = {
                "registration": {"domain": "drain.test.registrar",
                                  "type": "host"},
                "adminIp": "10.66.66.67",
                "zookeeper": {
                    "servers": [{"host": server.host, "port": server.port}],
                    "timeout": 30000,
                },
            }
            cfg_path = tmp_path / "config.json"
            cfg_path.write_text(json.dumps(config))
            proc = subprocess.Popen(
                [sys.executable, "-m", "registrar_tpu", "-f", str(cfg_path)],
                cwd=REPO,
                stdout=subprocess.DEVNULL,
                stderr=subprocess.STDOUT,
                env={**os.environ, "PYTHONPATH": REPO},
            )
            try:
                hostname = socket.gethostname()
                node = f"/registrar/test/drain/{hostname}"
                for _ in range(100):
                    if await observer.exists(node):
                        break
                    await asyncio.sleep(0.1)
                else:
                    raise AssertionError("znode never appeared")
                proc.send_signal(signal.SIGTERM)
                proc.wait(timeout=10)
                # gone well before the 30s session timeout
                assert await observer.exists(node) is None
            finally:
                if proc.poll() is None:
                    proc.kill()
        finally:
            await observer.close()
            await server.stop()


class TestGracefulStopOrdering:
    """ISSUE 5 satellite: the shutdown sequence is ordered — health
    checking stops first (no transition may race the exit), then the
    deregistration, then the client close, then the exit code."""

    async def test_drain_stop_runs_health_deregister_close_in_order(
        self, tmp_path, monkeypatch
    ):
        from registrar_tpu import main as main_mod
        from registrar_tpu.agent import RegistrarEvents
        from registrar_tpu.config import parse_config
        from registrar_tpu.main import run

        server = await ZKServer().start()
        observer = await ZKClient([server.address]).connect()
        order = []

        real_stop = RegistrarEvents.stop

        def rec_stop(self):
            order.append("health-stop")
            return real_stop(self)

        monkeypatch.setattr(RegistrarEvents, "stop", rec_stop)

        real_unreg = main_mod._drain_unregister

        async def rec_unreg(zk, nodes, lg):
            order.append("deregister")
            return await real_unreg(zk, nodes, lg)

        monkeypatch.setattr(main_mod, "_drain_unregister", rec_unreg)

        real_close = ZKClient.close

        async def rec_close(self):
            order.append("close")
            return await real_close(self)

        monkeypatch.setattr(ZKClient, "close", rec_close)

        cfg = parse_config({
            "registration": {"domain": "order.e2e.registrar",
                             "type": "host",
                             "heartbeatInterval": 100},
            "adminIp": "10.66.66.70",
            "zookeeper": {
                "servers": [{"host": server.host, "port": server.port}],
                "timeout": 10000,
            },
            "healthCheck": {"command": "true", "interval": 60000},
            "restart": {"stateFile": str(tmp_path / "s.json"),
                        "mode": "drain"},
        })
        task = asyncio.create_task(
            run(cfg, _exit=lambda c: order.append(("exit", c)))
        )
        try:
            node = f"/registrar/e2e/order/{socket.gethostname()}"
            for _ in range(200):
                if await observer.exists(node):
                    break
                await asyncio.sleep(0.05)
            else:
                raise AssertionError("znode never appeared")
            os.kill(os.getpid(), signal.SIGTERM)
            await asyncio.wait_for(task, timeout=15)
            order.append("returned")
            assert order == [
                "health-stop", "deregister", "close", "returned",
            ]
            # clean exit: code 0 means _exit was never invoked
            assert ("exit", 1) not in order
            assert await observer.exists(node) is None
        finally:
            if not task.done():
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass
            await observer.close()
            await server.stop()


class TestEventLoopInstall:
    """zookeeper.eventLoop (ISSUE 11): uvloop opt-in, import-guarded,
    default path untouched — parity pinned here."""

    def _cfg(self, event_loop=None):
        from registrar_tpu.config import parse_config

        raw = {
            "registration": {"domain": "a.b", "type": "host"},
            "zookeeper": {"servers": [{"host": "h", "port": 1}]},
        }
        if event_loop is not None:
            raw["zookeeper"]["eventLoop"] = event_loop
        return parse_config(raw)

    def test_default_changes_no_policy(self):
        from registrar_tpu.main import install_event_loop

        before = asyncio.get_event_loop_policy()
        assert install_event_loop(self._cfg()) == "asyncio"
        assert install_event_loop(self._cfg("asyncio")) == "asyncio"
        assert asyncio.get_event_loop_policy() is before

    def test_uvloop_missing_falls_back_with_warning(self, monkeypatch, caplog):
        # The container has no uvloop: the import guard must fall back
        # to the stdlib loop with one warning, never fail the start.
        import builtins
        import logging

        from registrar_tpu.main import install_event_loop

        real_import = builtins.__import__

        def deny_uvloop(name, *a, **kw):
            if name == "uvloop":
                raise ImportError("No module named 'uvloop'")
            return real_import(name, *a, **kw)

        monkeypatch.setattr(builtins, "__import__", deny_uvloop)
        before = asyncio.get_event_loop_policy()
        with caplog.at_level(logging.WARNING, logger="registrar"):
            assert install_event_loop(self._cfg("uvloop")) == "asyncio"
        assert asyncio.get_event_loop_policy() is before
        assert any("uvloop" in r.message for r in caplog.records)

    def test_uvloop_present_installs_policy(self, monkeypatch):
        # A stand-in uvloop module proves the happy path without the
        # real dependency (which is deliberately not bundled).
        import types

        from registrar_tpu.main import install_event_loop

        class _FakePolicy(asyncio.DefaultEventLoopPolicy):
            pass

        fake = types.ModuleType("uvloop")
        fake.EventLoopPolicy = _FakePolicy
        monkeypatch.setitem(sys.modules, "uvloop", fake)
        before = asyncio.get_event_loop_policy()
        try:
            assert install_event_loop(self._cfg("uvloop")) == "uvloop"
            assert isinstance(asyncio.get_event_loop_policy(), _FakePolicy)
        finally:
            asyncio.set_event_loop_policy(before)

    async def test_wire_parity_is_loop_independent(self):
        # The daemon's wire behavior must not depend on the loop choice:
        # the same registration through the same server yields the same
        # znodes + payload bytes whichever policy is installed (here:
        # the stdlib one, the only loop shipped — uvloop itself is
        # exercised only when an operator installs it).
        from registrar_tpu.registration import register

        server = await ZKServer().start()
        client = await ZKClient([server.address]).connect()
        try:
            nodes = await register(
                client, {"domain": "loop.parity.test", "type": "host"},
                admin_ip="10.9.9.9", hostname="loophost", settle_delay=0,
            )
            (data, st) = await client.get(nodes[0])
            assert st.ephemeral_owner == client.session_id
            assert b'"type":"host"' in data.replace(b" ", b"")
        finally:
            await client.close()
            await server.stop()
