"""Namespace-sharded serve tier (ISSUE 12): ring, protocol, worker,
router, resharding, crash supervision, stale-while-unreachable.

Process-spawning tests are deliberately consolidated (a worker costs an
interpreter start); the pure pieces — the hash ring's stability and
movement bounds, the frame codecs — are exercised exhaustively because
they are the contracts everything else rides on.
"""

import asyncio
import json
import os
import subprocess
import sys
import time

import pytest

from registrar_tpu import binderview, trace, traceview
from registrar_tpu.registration import register
from registrar_tpu.shard import (
    OP_RESOLVE,
    OP_STATUS,
    OP_TRACE,
    STATUS_ERR,
    STATUS_OK,
    TRACE_FLAG,
    Channel,
    HashRing,
    ShardClient,
    ShardDirectClient,
    ShardError,
    ShardRouter,
    ShardWorker,
    decode_resolution,
    encode_resolution,
    pack_frame,
    pack_request,
    pack_resolve,
    resolve_name,
)
from registrar_tpu.testing.server import ZKServer
from registrar_tpu.zk.client import ZKClient


# ---------------------------------------------------------------------------
# HashRing: the contract every other piece rides on
# ---------------------------------------------------------------------------


def _sample_domains(k: int):
    return [f"svc{i}.shardtest.joyent.us" for i in range(k)]


class TestHashRing:
    def test_deterministic_within_process(self):
        a = HashRing(range(4))
        b = HashRing(range(4))
        for dom in _sample_domains(100):
            assert a.owner(dom) == b.owner(dom)

    def test_stable_across_process_restarts(self):
        # The reason for BLAKE2 over hash(): Python string hashing is
        # salted per process, and a restarted router must re-derive the
        # EXACT ring or every worker's warm slice is orphaned.  A fresh
        # interpreter (its own hash salt) must agree on every owner.
        domains = _sample_domains(24)
        local = {d: HashRing(range(4)).owner(d) for d in domains}
        script = (
            "import json,sys;"
            "from registrar_tpu.shard import HashRing;"
            "r=HashRing(range(4));"
            "print(json.dumps({d: r.owner(d) for d in json.load(sys.stdin)}))"
        )
        env = dict(os.environ)
        env["PYTHONPATH"] = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )
        out = subprocess.run(
            [sys.executable, "-c", script],
            input=json.dumps(domains), capture_output=True, text=True,
            env=env, check=True,
        )
        assert json.loads(out.stdout) == local

    def test_every_shard_owns_a_slice(self):
        ring = HashRing(range(8))
        owners = {ring.owner(d) for d in _sample_domains(400)}
        assert owners == set(range(8))

    @pytest.mark.parametrize("n", [2, 4, 8])
    def test_reshard_movement_bounded(self, n):
        # Consistent hashing's whole point: growing N -> N+1 moves only
        # ~K/(N+1) domains.  The ring is deterministic, so this is a
        # fact being pinned, not a distribution being sampled.  Bound:
        # ceil(K/N) + slack (the acceptance criterion's shape).
        k = 240
        domains = _sample_domains(k)
        old = HashRing(range(n))
        new = HashRing(range(n + 1))
        moved = old.moved(new, domains)
        bound = -(-k // n) + k // 10 + 2
        assert len(moved) <= bound, (len(moved), bound)
        # ...and every moved domain landed on the NEW shard or a
        # rebalanced slot; domains that didn't move keep their owner.
        for dom in domains:
            if dom not in moved:
                assert old.owner(dom) == new.owner(dom)

    def test_shrink_movement_bounded(self):
        k = 240
        domains = _sample_domains(k)
        old = HashRing(range(5))
        new = HashRing(range(4))
        moved = old.moved(new, domains)
        # Removing one of five shards strands ~K/5 domains; everything
        # else must stay put.
        assert len(moved) <= -(-k // 5) + k // 10 + 2
        for dom in domains:
            if old.owner(dom) in range(4):
                assert new.owner(dom) == old.owner(dom)

    def test_empty_ring_refused(self):
        with pytest.raises(ValueError):
            HashRing([])


# ---------------------------------------------------------------------------
# Frame + resolution codecs
# ---------------------------------------------------------------------------


class TestCodecs:
    def test_resolution_roundtrip(self):
        res = binderview.Resolution(
            answers=[binderview.Answer("a.b.us", "A", 30, "10.0.0.1")],
            additionals=[
                binderview.Answer("h.a.b.us", "A", 60, "10.0.0.2")
            ],
        )
        out = decode_resolution(encode_resolution(res))
        assert [str(a) for a in out.answers] == [str(a) for a in res.answers]
        assert [str(a) for a in out.additionals] == [
            str(a) for a in res.additionals
        ]

    def test_resolve_request_name_extraction(self):
        body = pack_resolve("MyDomain.Example.US", "SRV", live=True)
        assert resolve_name(body) == "MyDomain.Example.US"
        assert body[0] & 1  # live flag
        frame = pack_frame(7, OP_RESOLVE, body)
        assert int.from_bytes(frame[:4], "big") == len(frame) - 4


# ---------------------------------------------------------------------------
# Trace-context wire extension (ISSUE 13): parity + codec
# ---------------------------------------------------------------------------


class TestTraceWire:
    #: the PR-12 wire format, pinned BYTE FOR BYTE: an OP_RESOLVE
    #: request frame for ("web.parity.joyent.us", "A") with req_id 7.
    #: Tracing off must keep emitting exactly this — a drifted frame
    #: breaks every already-deployed worker mid-rolling-upgrade.
    GOLDEN_RESOLVE_FRAME = bytes.fromhex(
        "0000001c00000007010001417765622e7061726974792e6a6f79656e742e7573"
    )

    def test_untraced_request_is_byte_identical_to_pr12(self):
        body = pack_resolve("web.parity.joyent.us", "A")
        assert pack_frame(7, OP_RESOLVE, body) == self.GOLDEN_RESOLVE_FRAME
        # pack_request without context IS pack_frame — the codec the
        # Channel uses cannot drift from the pinned format.
        assert (
            pack_request(7, OP_RESOLVE, body) == self.GOLDEN_RESOLVE_FRAME
        )

    def test_traced_request_gates_context_behind_the_flag_bit(self):
        body = pack_resolve("web.parity.joyent.us", "A")
        ctx = (0x0123456789ABCDEF, 0xFEDCBA9876543210, 1)
        frame = pack_request(7, OP_RESOLVE, body, trace_ctx=ctx)
        # length prefix grew by exactly the 17-byte context block
        assert int.from_bytes(frame[:4], "big") == len(
            self.GOLDEN_RESOLVE_FRAME
        ) - 4 + 17
        assert frame[8] == OP_RESOLVE | TRACE_FLAG
        assert frame[9:17] == (0x0123456789ABCDEF).to_bytes(8, "big")
        assert frame[17:25] == (0xFEDCBA9876543210).to_bytes(8, "big")
        assert frame[25] == 1
        # the body rides after the block, unchanged
        assert frame[26:] == bytes(body)

    async def test_untraced_reply_carries_no_flag_on_the_raw_socket(
        self, tmp_path
    ):
        """A worker answering an untraced request must emit the plain
        PR-12 reply — no flag bit, no worker_us block — asserted on the
        RAW socket (the Channel would strip an extension silently)."""
        server = await ZKServer().start()
        client = await ZKClient([server.address]).connect()
        worker = None
        try:
            await register(client, REG, admin_ip="10.6.0.1",
                           hostname="h1", settle_delay=0)
            worker = ShardWorker(
                _worker_spec(server, str(tmp_path / "w.sock"))
            )
            await worker.start()
            reader, writer = await asyncio.open_unix_connection(
                worker.socket_path
            )
            try:
                writer.write(
                    pack_frame(3, OP_RESOLVE, pack_resolve(REG["domain"]))
                )
                await writer.drain()
                head = await reader.readexactly(4)
                frame = await reader.readexactly(
                    int.from_bytes(head, "big")
                )
                assert frame[:4] == (3).to_bytes(4, "big")
                assert frame[4] == STATUS_OK  # no TRACE_FLAG bit
                res = decode_resolution(frame[5:])
                assert [a.data for a in res.answers] == ["10.6.0.1"]
            finally:
                writer.close()
        finally:
            if worker is not None:
                await worker.close()
            await client.close()
            await server.stop()

    async def test_flagged_frame_too_short_answers_error_not_hang(
        self, tmp_path
    ):
        """A length-valid frame with the TRACE_FLAG bit but a body too
        short for the 17-byte context block must get a STATUS_ERR reply
        — a dead handler task would leave the requester (whose future
        has no timeout) waiting forever."""
        server = await ZKServer().start()
        client = await ZKClient([server.address]).connect()
        worker = None
        chan = None
        try:
            await register(client, REG, admin_ip="10.6.0.1",
                           hostname="h1", settle_delay=0)
            worker = ShardWorker(
                _worker_spec(server, str(tmp_path / "w.sock"))
            )
            await worker.start()
            reader, writer = await asyncio.open_unix_connection(
                worker.socket_path
            )
            try:
                writer.write(
                    pack_frame(9, OP_RESOLVE | TRACE_FLAG, b"xx")
                )
                await writer.drain()
                head = await asyncio.wait_for(
                    reader.readexactly(4), timeout=5
                )
                frame = await reader.readexactly(
                    int.from_bytes(head, "big")
                )
                assert frame[:4] == (9).to_bytes(4, "big")
                assert frame[4] == STATUS_ERR
                assert b"too short" in frame[5:]
            finally:
                writer.close()
            # ...and the worker survived: a normal request still answers.
            chan = await Channel.open(worker.socket_path)
            status, body = await chan.request(
                OP_RESOLVE, pack_resolve(REG["domain"], "A")
            )
            assert status == STATUS_OK and decode_resolution(body).answers
        finally:
            if chan is not None:
                await chan.close()
            if worker is not None:
                await worker.close()
            await client.close()
            await server.stop()

    async def test_worker_adopts_context_and_reports_duration(
        self, tmp_path
    ):
        """A traced request's resolve subtree chains under the WIRE
        parent id, OP_TRACE hands the fragment back filtered by trace
        id, and the reply's worker_us block lands as the caller span's
        ``worker`` mark."""
        server = await ZKServer().start()
        client = await ZKClient([server.address]).connect()
        worker = None
        chan = None
        tracer = trace.Tracer(sample_rate=1.0)
        try:
            await register(client, REG, admin_ip="10.6.0.1",
                           hostname="h1", settle_delay=0)
            worker = ShardWorker(
                _worker_spec(server, str(tmp_path / "w.sock"))
            )
            await worker.start()
            # In-process worker: hang one private tracer on every
            # instrumented layer (the spawned-process path installs a
            # process-global one from spec["trace"] instead).
            worker.tracer = tracer
            worker.cache.tracer = tracer
            worker.zk.tracer = tracer
            chan = await Channel.open(worker.socket_path)

            caller = trace.Tracer(sample_rate=1.0)
            with caller.span("client.call") as sp:
                ctx = trace.current_context()
                status, body = await chan.request(
                    OP_RESOLVE, pack_resolve(REG["domain"], "A"),
                    trace_ctx=ctx, span=sp,
                )
            assert status == STATUS_OK
            assert decode_resolution(body).answers
            # the Channel stripped the extension and stamped the mark
            assert sp.marks is not None and sp.marks["worker"] > 0

            trace_id = sp.trace_id
            status, body = await chan.request(
                OP_TRACE, json.dumps({"trace_id": trace_id}).encode()
            )
            assert status == STATUS_OK
            dump = json.loads(bytes(body).decode())
            assert dump["shard"] == 0 and dump["pid"] == os.getpid()
            names = {e["name"] for e in dump["entries"]}
            assert "resolve.query" in names  # cold fill: zk ops too
            assert "cache.fill" in names and "zk.op" in names
            for entry in dump["entries"]:
                assert entry["trace_id"] == trace_id
            # the subtree parents under the WIRE span id
            resolve_spans = [
                e for e in dump["entries"] if e["name"] == "resolve.query"
            ]
            assert resolve_spans[0]["parent_id"] == sp.span_id
            # ...and assembles under the caller with zero orphans
            tree = traceview.assemble(
                caller.dump()["entries"] + dump["entries"], trace_id
            )
            assert tree["orphans"] == 0
            assert tree["roots"][0]["name"] == "client.call"
        finally:
            if chan is not None:
                await chan.close()
            if worker is not None:
                await worker.close()
            await client.close()
            await server.stop()

    async def test_unsampled_context_propagates_but_records_nothing(
        self, tmp_path
    ):
        server = await ZKServer().start()
        client = await ZKClient([server.address]).connect()
        worker = None
        chan = None
        tracer = trace.Tracer(sample_rate=1.0)
        try:
            await register(client, REG, admin_ip="10.6.0.1",
                           hostname="h1", settle_delay=0)
            worker = ShardWorker(
                _worker_spec(server, str(tmp_path / "w.sock"))
            )
            await worker.start()
            worker.tracer = tracer
            worker.cache.tracer = tracer
            worker.zk.tracer = tracer
            chan = await Channel.open(worker.socket_path)
            status, _body = await chan.request(
                OP_RESOLVE, pack_resolve(REG["domain"], "A"),
                trace_ctx=(0x1111, 0x2222, 0),  # sampled=0
            )
            assert status == STATUS_OK
            assert tracer.dump()["entries"] == []
        finally:
            if chan is not None:
                await chan.close()
            if worker is not None:
                await worker.close()
            await client.close()
            await server.stop()


# ---------------------------------------------------------------------------
# In-process worker: protocol ops, warm set, stale-while-unreachable
# ---------------------------------------------------------------------------


REG = {
    "domain": "one.shardtest.joyent.us",
    "type": "load_balancer",
    "service": {
        "type": "service",
        "service": {"srvce": "_http", "proto": "_tcp", "port": 80},
    },
}


def _worker_spec(server, path, shard=0):
    return {
        "socket": path,
        "shard": shard,
        "shards": 1,
        "servers": [[server.host, server.port]],
        "timeoutMs": 4000,
    }


async def test_worker_protocol_and_warm_set(tmp_path):
    server = await ZKServer().start()
    client = await ZKClient([server.address]).connect()
    worker = None
    chan = None
    try:
        await register(client, REG, admin_ip="10.6.0.1", hostname="h1",
                       settle_delay=0)
        worker = ShardWorker(
            _worker_spec(server, str(tmp_path / "w.sock"))
        )
        await worker.start()
        chan = await Channel.open(worker.socket_path)

        status, body = await chan.request(
            OP_RESOLVE, pack_resolve(REG["domain"], "A")
        )
        assert status == STATUS_OK
        res = decode_resolution(body)
        assert [a.data for a in res.answers] == ["10.6.0.1"]
        assert (REG["domain"], "A") in worker.warm

        # A second resolve is a cache hit in the worker's ZKCache.
        hits_before = worker.cache.stats["hits"]
        await chan.request(OP_RESOLVE, pack_resolve(REG["domain"], "A"))
        assert worker.cache.stats["hits"] > hits_before

        # STATUS carries the rollup fields the router aggregates.
        status, body = await chan.request(OP_STATUS, b"")
        st = json.loads(bytes(body).decode())
        assert st["resolves_total"] == 2
        assert st["session"]["connected"] is True
        assert st["authoritative"] is True

        # Unknown op answers an error frame, not a dead connection.
        status, body = await chan.request(99, b"")
        assert status == STATUS_ERR
        assert b"unknown op" in bytes(body)

        # The warm set is LRU-bounded by maxEntries.
        worker.max_entries = 2
        for i in range(4):
            worker._touch(f"d{i}.x.us", "A", b"{}")
        assert len(worker.warm) == 2
        assert ("d3.x.us", "A") in worker.warm
    finally:
        if chan is not None:
            await chan.close()
        if worker is not None:
            await worker.close()
        await client.close()
        await server.stop()


async def test_worker_stale_while_unreachable(tmp_path):
    """A transient backend outage serves the bounded-age last-known-good
    answer instead of failing the slice; an explicit live read still
    fails truthfully, and an expired record is not served."""
    server = await ZKServer().start()
    client = await ZKClient([server.address]).connect()
    worker = None
    chan = None
    try:
        await register(client, REG, admin_ip="10.6.0.1", hostname="h1",
                       settle_delay=0)
        worker = ShardWorker(
            _worker_spec(server, str(tmp_path / "w.sock"))
        )
        await worker.start()
        chan = await Channel.open(worker.socket_path)
        status, body = await chan.request(
            OP_RESOLVE, pack_resolve(REG["domain"], "A")
        )
        assert status == STATUS_OK
        warm_answer = bytes(body)

        await client.close()
        await server.stop()  # the whole backend goes away
        # Cached resolves fall back to the last-known-good bytes.
        deadline = time.monotonic() + 5
        while True:
            status, body = await chan.request(
                OP_RESOLVE, pack_resolve(REG["domain"], "A")
            )
            if status == STATUS_OK:
                break
            # The worker may still have been flushing its cache when the
            # first post-outage resolve arrived; it must settle into
            # stale serving, not erroring.
            assert time.monotonic() < deadline, bytes(body)
            await asyncio.sleep(0.05)
        assert bytes(body) == warm_answer
        assert worker.stale_serves >= 1

        # An explicit live read never serves stale.
        status, body = await chan.request(
            OP_RESOLVE, pack_resolve(REG["domain"], "A", live=True)
        )
        assert status == STATUS_ERR

        # Past the bound, the record is too old to lie about.
        worker.max_stale_s = 0.0
        await asyncio.sleep(0.01)
        status, body = await chan.request(
            OP_RESOLVE, pack_resolve(REG["domain"], "A")
        )
        assert status == STATUS_ERR
    finally:
        if chan is not None:
            await chan.close()
        if worker is not None:
            await worker.close()


# ---------------------------------------------------------------------------
# The full tier: parity, resharding, crash supervision
# ---------------------------------------------------------------------------


#: README-derived resolve scenarios (the test_binderview shapes): a
#: service fleet (A + SRV), a direct host record, an alias, an absent
#: domain — sharded-vs-single parity must hold across all of them
def _parity_registrations():
    return [
        (
            {
                "domain": "web.parity.joyent.us",
                "type": "load_balancer",
                "aliases": ["alias.web.parity.joyent.us"],
                "service": {
                    "type": "service",
                    "service": {
                        "srvce": "_http", "proto": "_tcp", "port": 80,
                    },
                },
            },
            "10.77.0.%d",
            3,
        ),
        (
            {"domain": "lonely.parity.joyent.us", "type": "host"},
            "10.78.0.%d",
            1,
        ),
    ]


_PARITY_QUERIES = (
    ("web.parity.joyent.us", "A"),
    ("_http._tcp.web.parity.joyent.us", "SRV"),
    ("alias.web.parity.joyent.us", "A"),
    ("lonely.parity.joyent.us", "A"),
    ("absent.parity.joyent.us", "A"),
)


async def test_sharded_vs_single_cache_parity(tmp_path):
    """The tier must answer byte-for-byte what an in-process resolve
    over a plain client answers, for every README scenario shape —
    through the router relay AND the direct data plane."""
    server = await ZKServer().start()
    clients = []
    router = None
    sc = dc = None
    try:
        for reg, ip_fmt, instances in _parity_registrations():
            for i in range(instances):
                cl = await ZKClient([server.address]).connect()
                clients.append(cl)
                await register(
                    cl, reg, admin_ip=ip_fmt % i, hostname=f"i{i}",
                    settle_delay=0,
                )
        observer = await ZKClient([server.address]).connect()
        clients.append(observer)
        router = await ShardRouter(
            [server.address], 2, str(tmp_path / "parity.sock"),
            attach_spread="any",
        ).start()
        sc = await ShardClient(router.socket_path).connect()
        dc = await ShardDirectClient(router.socket_path).connect()
        for name, qtype in _PARITY_QUERIES:
            expected = await binderview.resolve(observer, name, qtype)
            for res in (
                await sc.resolve(name, qtype),
                await dc.resolve(name, qtype),
                await sc.resolve(name, qtype, live=True),
            ):
                assert [str(a) for a in res.answers] == [
                    str(a) for a in expected.answers
                ], (name, qtype)
                assert [str(a) for a in res.additionals] == [
                    str(a) for a in expected.additionals
                ], (name, qtype)
    finally:
        if sc is not None:
            await sc.close()
        if dc is not None:
            await dc.close()
        if router is not None:
            await router.stop()
        for cl in clients:
            await cl.close()
        await server.stop()


async def test_reshard_bounded_movement_zero_errors(tmp_path):
    """Resharding 2 -> 3 mid-traffic: a 10 ms-poll resolver sees ZERO
    errors, the warm handoff moves only domains whose owner changed
    (<= ceil(K/N) + slack of the K warm domains), and the moved slice
    answers warm from its new owner."""
    server = await ZKServer().start()
    client = await ZKClient([server.address]).connect()
    router = None
    sc = None
    try:
        domains = []
        for i in range(12):
            dom = f"svc{i}.reshard.joyent.us"
            await register(
                client,
                {
                    "domain": dom,
                    "type": "load_balancer",
                    "service": {
                        "type": "service",
                        "service": {
                            "srvce": "_http", "proto": "_tcp", "port": 80,
                        },
                    },
                },
                admin_ip=f"10.9.0.{i}", hostname="h0", settle_delay=0,
            )
            domains.append(dom)
        router = await ShardRouter(
            [server.address], 2, str(tmp_path / "reshard.sock"),
            attach_spread="any",
        ).start()
        sc = await ShardClient(router.socket_path).connect()
        for dom in domains:  # warm every domain into the tier
            res = await sc.resolve(dom, "A")
            assert res.answers

        old_ring = router.ring
        polling = True
        errors = []

        async def poll():
            polled = 0
            while polling:
                for dom in domains:
                    try:
                        res = await sc.resolve(dom, "A")
                        if not res.answers:
                            errors.append(f"{dom}: empty")
                    except Exception as err:  # noqa: BLE001 - the tally IS the assertion
                        errors.append(f"{dom}: {err!r}")
                    polled += 1
                await asyncio.sleep(0.01)
            return polled

        poller = asyncio.ensure_future(poll())
        outcome = await router.reshard(3)
        await asyncio.sleep(0.05)
        polling = False
        polled = await poller
        assert polled > 0
        assert errors == [], errors[:5]

        # Movement bound over the tier's warm set (12 domains + the
        # negative/odd paths the warm set may carry).
        k = len(domains)
        moved_domains = old_ring.moved(router.ring, domains)
        assert len(moved_domains) <= -(-k // 2) + k // 4 + 1
        assert outcome["moved"] >= len(moved_domains)
        assert outcome["shards"] == 3
        assert router.generation == 1

        # The moved domains answer from their NEW owner's warm set: its
        # worker pre-resolved them before the flip.
        st = await router.status()
        warm_total = sum(
            info["warm"] for info in st["shards"].values()
        )
        assert warm_total >= k

        # No-op reshard moves nothing.
        assert (await router.reshard(3))["moved"] == 0
    finally:
        if sc is not None:
            await sc.close()
        if router is not None:
            await router.stop()
        await client.close()
        await server.stop()


async def test_worker_crash_respawn_e2e(tmp_path):
    """SIGKILL one worker under a 10 ms-poll resolver: the surviving
    shards' slices answer with ZERO errors throughout, the dead slice
    recovers within the respawn bound, and the router's status/metrics
    record the crash."""
    from registrar_tpu import metrics as metrics_mod

    server = await ZKServer().start()
    client = await ZKClient([server.address]).connect()
    router = None
    sc = None
    try:
        domains = []
        for i in range(8):
            dom = f"svc{i}.crash.joyent.us"
            await register(
                client,
                {
                    "domain": dom,
                    "type": "load_balancer",
                    "service": {
                        "type": "service",
                        "service": {
                            "srvce": "_http", "proto": "_tcp", "port": 80,
                        },
                    },
                },
                admin_ip=f"10.10.0.{i}", hostname="h0", settle_delay=0,
            )
            domains.append(dom)
        router = await ShardRouter(
            [server.address], 2, str(tmp_path / "crash.sock"),
            attach_spread="any", poll_interval_s=0.2,
        ).start()
        registry = metrics_mod.instrument_shards(router)
        sc = await ShardClient(router.socket_path).connect()
        for dom in domains:
            assert (await sc.resolve(dom, "A")).answers

        victim = router.ring.owner(domains[0])
        victim_doms = [
            d for d in domains if router.ring.owner(d) == victim
        ]
        surviving = [d for d in domains if d not in victim_doms]
        assert surviving, "sample too small to cover both shards"

        surviving_errors = []
        victim_recovered_at = None
        polling = True

        async def poll():
            nonlocal victim_recovered_at
            while polling:
                for dom in surviving:
                    try:
                        res = await sc.resolve(dom, "A")
                        if not res.answers:
                            surviving_errors.append(f"{dom}: empty")
                    except Exception as err:  # noqa: BLE001 - the tally IS the assertion
                        surviving_errors.append(f"{dom}: {err!r}")
                if victim_recovered_at is None:
                    try:
                        if (await sc.resolve(victim_doms[0], "A")).answers:
                            victim_recovered_at = time.monotonic()
                    except Exception:  # noqa: BLE001 - still down
                        pass
                await asyncio.sleep(0.01)

        poller = asyncio.ensure_future(poll())
        await asyncio.sleep(0.1)  # healthy polls on both slices first
        killed_at = time.monotonic()
        router.kill_worker(victim)
        victim_recovered_at = None  # only post-kill recovery counts
        deadline = killed_at + 20
        while victim_recovered_at is None and time.monotonic() < deadline:
            await asyncio.sleep(0.02)
        polling = False
        await poller

        assert victim_recovered_at is not None, "victim slice never recovered"
        assert surviving_errors == [], surviving_errors[:5]

        st = await router.status()
        assert st["serve"]["respawns_total"] == 1
        assert st["shards"][str(victim)]["respawns"] == 1
        assert not st["degraded"]
        # metrics rollup saw the respawn; resolves_total stayed monotonic
        respawns = registry.get("registrar_shard_respawns_total")
        assert respawns.value({"shard": str(victim)}) == 1.0
    finally:
        if sc is not None:
            await sc.close()
        if router is not None:
            await router.stop()
        await client.close()
        await server.stop()


async def test_cross_process_trace_e2e(tmp_path):
    """ISSUE 13 acceptance: ONE resolve through the tier yields ONE
    merged tree — the caller's root span, the router's shard.relay
    (with its queue/socket/worker mark split), the owning worker's
    resolve.query subtree and its zk.op leaves — all on one trace id,
    assembled across process boundaries.  Then the boundaries move:
    context still joins across a worker respawn and an in-place
    reshard (the moved domain's next resolve parents under its NEW
    owner), and a SIGKILLed worker's lost fragment degrades to a
    visibly incomplete tree, never a collect failure.

    One consolidated test: every scenario reuses the spawned tier (a
    worker costs an interpreter start, the file's standing policy)."""
    from registrar_tpu import metrics as metrics_mod

    server = await ZKServer().start()
    client = await ZKClient([server.address]).connect()
    router = None
    sc = None
    tracer = trace.Tracer(sample_rate=1.0)
    try:
        # Deterministic domain choice that covers both shards of the
        # 2-ring AND includes at least one domain whose owner changes
        # under the 3-ring (the reshard-propagation leg needs a mover;
        # the rings are pure functions, so scan-and-pick is exact).
        ring2, ring3 = HashRing(range(2)), HashRing(range(3))
        domains, covered, movers = [], set(), 0
        for i in range(256):
            dom = f"svc{i}.traced.joyent.us"
            is_mover = ring2.owner(dom) != ring3.owner(dom)
            if len(domains) < 8 or (is_mover and movers < 2):
                domains.append(dom)
                covered.add(ring2.owner(dom))
                movers += is_mover
            if len(domains) >= 8 and movers >= 2 and len(covered) == 2:
                break
        assert movers >= 1 and len(covered) == 2
        for i, dom in enumerate(domains):
            await register(
                client,
                {
                    "domain": dom,
                    "type": "load_balancer",
                    "service": {
                        "type": "service",
                        "service": {
                            "srvce": "_http", "proto": "_tcp", "port": 80,
                        },
                    },
                },
                admin_ip=f"10.11.0.{i}", hostname="h0", settle_delay=0,
            )
        router = ShardRouter(
            [server.address], 2, str(tmp_path / "traced.sock"),
            attach_spread="any", poll_interval_s=0.2,
            worker_trace={"sampleRate": 1.0, "maxSpans": 2048},
        )
        router.tracer = tracer
        await router.start()
        registry = metrics_mod.instrument_shards(router)
        sc = await ShardClient(router.socket_path).connect()

        # --- the headline: one resolve, one tree --------------------------
        with tracer.span("client.root") as root:
            res = await sc.resolve(domains[0], "A")
        assert res.answers
        owner = router.ring.owner(domains[0])
        tree = await router.collect_trace(root.trace_id)
        assert tree["trace_id"] == root.trace_id
        assert tree["orphans"] == 0
        assert tree["roots"][0]["name"] == "client.root"
        relay = tree["roots"][0]["children"][0]
        assert relay["name"] == "shard.relay"
        assert relay["attrs"]["shard"] == owner
        assert relay["proc"] == "router"
        # the queue/socket/worker split: both marks present
        assert "forwarded" in relay["marks"] and "worker" in relay["marks"]
        resolve_node = relay["children"][0]
        assert resolve_node["name"] == "resolve.query"
        assert resolve_node["proc"] == f"shard{owner}"
        # cold fill: the worker's zk.op leaves are in the SAME tree
        subtree_names = set()

        def walk(node):
            subtree_names.add(node["name"])
            for child in node["children"]:
                walk(child)

        walk(resolve_node)
        assert "cache.fill" in subtree_names and "zk.op" in subtree_names
        # the relay histogram observed the hop, labeled by owner
        relay_hist = registry.get("registrar_shard_relay_seconds")
        assert relay_hist.count({"shard": str(owner)}) == 1
        # the front socket serves the SAME assembly (OP_TRACE on the
        # router), which is what zkcli rides without a metrics listener
        via_socket = await sc.trace_tree(root.trace_id)
        assert via_socket["spans"] == tree["spans"]

        # --- context joins across a worker respawn ------------------------
        handle = router._workers[owner]
        old_seq = handle.seq
        router.kill_worker(owner)
        deadline = time.monotonic() + 20
        while not (handle.up and handle.seq != old_seq):
            assert time.monotonic() < deadline, "respawn never landed"
            await asyncio.sleep(0.05)
        with tracer.span("client.root") as root2:
            assert (await sc.resolve(domains[0], "A")).answers
        tree2 = await router.collect_trace(root2.trace_id)
        assert tree2["orphans"] == 0
        relay2 = tree2["roots"][0]["children"][0]
        assert relay2["children"][0]["name"] == "resolve.query"
        # the fragment came from the RESPAWNED worker process
        pids = {
            s.get("pid") for s in tree2["sources"]
            if s["proc"] == f"shard{owner}"
        }
        assert pids == {handle.proc.pid}

        # --- context joins across an in-place reshard ---------------------
        old_ring = router.ring
        await router.reshard(3)
        moved = old_ring.moved(router.ring, domains)
        assert moved, "sample too small for a moving domain"
        dom = moved[0]
        new_owner = router.ring.owner(dom)
        assert new_owner != old_ring.owner(dom)
        with tracer.span("client.root") as root3:
            assert (await sc.resolve(dom, "A")).answers
        tree3 = await router.collect_trace(root3.trace_id)
        relay3 = tree3["roots"][0]["children"][0]
        assert relay3["attrs"]["shard"] == new_owner
        resolve3 = relay3["children"][0]
        assert resolve3["name"] == "resolve.query"
        assert resolve3["proc"] == f"shard{new_owner}"

        # --- a SIGKILLed worker cannot silently erase the tree ------------
        router.respawn_enabled = False
        victim = router.ring.owner(domains[1])
        router.kill_worker(victim)
        deadline = time.monotonic() + 10
        while victim not in router.shards_down():
            assert time.monotonic() < deadline, "kill never detected"
            await asyncio.sleep(0.05)
        with tracer.span("client.root") as root4:
            with pytest.raises(ShardError):
                await sc.resolve(domains[1], "A")
        tree4 = await router.collect_trace(root4.trace_id)
        # the surviving fragments still assemble — root + the errored
        # relay — and the dead worker is NAMED in sources
        assert tree4["roots"][0]["name"] == "client.root"
        relay4 = tree4["roots"][0]["children"][0]
        assert relay4["name"] == "shard.relay"
        assert relay4["status"] == "error"
        assert any(
            s["proc"] == f"shard{victim}" and s.get("error")
            for s in tree4["sources"]
        )

        # --- orphan assembly: a parent nobody collected -------------------
        orphan_tree = traceview.assemble(
            [
                e
                for e in (await router.collect_trace(root3.trace_id))[
                    "roots"
                ][0]["children"][0]["children"][0:1]
            ],
            root3.trace_id,
        )
        # the resolve.query fragment alone (its relay parent withheld)
        # lands under <missing parent> instead of vanishing
        assert orphan_tree["orphans"] == 1
        assert orphan_tree["roots"][-1]["name"] == traceview.MISSING_PARENT
    finally:
        if sc is not None:
            await sc.close()
        if router is not None:
            await router.stop()
        await client.close()
        await server.stop()


async def test_router_degraded_without_respawn(tmp_path):
    """respawn_enabled=False (the SLO harness's repair-off mode): the
    dead shard stays down, status reports degraded, siblings keep
    serving."""
    server = await ZKServer().start()
    client = await ZKClient([server.address]).connect()
    router = None
    sc = None
    try:
        await register(client, REG, admin_ip="10.6.0.1", hostname="h1",
                       settle_delay=0)
        router = await ShardRouter(
            [server.address], 2, str(tmp_path / "down.sock"),
            attach_spread="any",
        ).start()
        router.respawn_enabled = False
        sc = await ShardClient(router.socket_path).connect()
        victim = router.ring.owner(REG["domain"])
        sibling = 1 - victim
        router.kill_worker(victim)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            st = await router.status()
            if st["degraded"]:
                break
            await asyncio.sleep(0.05)
        st = await router.status()
        assert st["degraded"] and st["shards_down"] == [victim]
        with pytest.raises(ShardError):
            await sc.resolve(REG["domain"], "A")
        # the sibling's slice still answers (any warm/fillable domain
        # it owns — ownership is a hint, workers answer anything)
        ring = router.ring
        for i in range(64):
            name = f"probe{i}.crash.joyent.us"
            if ring.owner(name) == sibling:
                res = await sc.resolve(name, "A")
                assert res.empty  # absent domain: clean empty, no error
                break
        else:
            pytest.fail("no sibling-owned probe name found")
    finally:
        if sc is not None:
            await sc.close()
        if router is not None:
            await router.stop()
        await client.close()
        await server.stop()
