"""Namespace-sharded serve tier (ISSUE 12): ring, protocol, worker,
router, resharding, crash supervision, stale-while-unreachable.

Process-spawning tests are deliberately consolidated (a worker costs an
interpreter start); the pure pieces — the hash ring's stability and
movement bounds, the frame codecs — are exercised exhaustively because
they are the contracts everything else rides on.
"""

import asyncio
import json
import os
import subprocess
import sys
import time

import pytest

from registrar_tpu import binderview
from registrar_tpu.registration import register
from registrar_tpu.shard import (
    OP_RESOLVE,
    OP_STATUS,
    STATUS_ERR,
    STATUS_OK,
    Channel,
    HashRing,
    ShardClient,
    ShardDirectClient,
    ShardError,
    ShardRouter,
    ShardWorker,
    decode_resolution,
    encode_resolution,
    pack_frame,
    pack_resolve,
    resolve_name,
)
from registrar_tpu.testing.server import ZKServer
from registrar_tpu.zk.client import ZKClient


# ---------------------------------------------------------------------------
# HashRing: the contract every other piece rides on
# ---------------------------------------------------------------------------


def _sample_domains(k: int):
    return [f"svc{i}.shardtest.joyent.us" for i in range(k)]


class TestHashRing:
    def test_deterministic_within_process(self):
        a = HashRing(range(4))
        b = HashRing(range(4))
        for dom in _sample_domains(100):
            assert a.owner(dom) == b.owner(dom)

    def test_stable_across_process_restarts(self):
        # The reason for BLAKE2 over hash(): Python string hashing is
        # salted per process, and a restarted router must re-derive the
        # EXACT ring or every worker's warm slice is orphaned.  A fresh
        # interpreter (its own hash salt) must agree on every owner.
        domains = _sample_domains(24)
        local = {d: HashRing(range(4)).owner(d) for d in domains}
        script = (
            "import json,sys;"
            "from registrar_tpu.shard import HashRing;"
            "r=HashRing(range(4));"
            "print(json.dumps({d: r.owner(d) for d in json.load(sys.stdin)}))"
        )
        env = dict(os.environ)
        env["PYTHONPATH"] = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )
        out = subprocess.run(
            [sys.executable, "-c", script],
            input=json.dumps(domains), capture_output=True, text=True,
            env=env, check=True,
        )
        assert json.loads(out.stdout) == local

    def test_every_shard_owns_a_slice(self):
        ring = HashRing(range(8))
        owners = {ring.owner(d) for d in _sample_domains(400)}
        assert owners == set(range(8))

    @pytest.mark.parametrize("n", [2, 4, 8])
    def test_reshard_movement_bounded(self, n):
        # Consistent hashing's whole point: growing N -> N+1 moves only
        # ~K/(N+1) domains.  The ring is deterministic, so this is a
        # fact being pinned, not a distribution being sampled.  Bound:
        # ceil(K/N) + slack (the acceptance criterion's shape).
        k = 240
        domains = _sample_domains(k)
        old = HashRing(range(n))
        new = HashRing(range(n + 1))
        moved = old.moved(new, domains)
        bound = -(-k // n) + k // 10 + 2
        assert len(moved) <= bound, (len(moved), bound)
        # ...and every moved domain landed on the NEW shard or a
        # rebalanced slot; domains that didn't move keep their owner.
        for dom in domains:
            if dom not in moved:
                assert old.owner(dom) == new.owner(dom)

    def test_shrink_movement_bounded(self):
        k = 240
        domains = _sample_domains(k)
        old = HashRing(range(5))
        new = HashRing(range(4))
        moved = old.moved(new, domains)
        # Removing one of five shards strands ~K/5 domains; everything
        # else must stay put.
        assert len(moved) <= -(-k // 5) + k // 10 + 2
        for dom in domains:
            if old.owner(dom) in range(4):
                assert new.owner(dom) == old.owner(dom)

    def test_empty_ring_refused(self):
        with pytest.raises(ValueError):
            HashRing([])


# ---------------------------------------------------------------------------
# Frame + resolution codecs
# ---------------------------------------------------------------------------


class TestCodecs:
    def test_resolution_roundtrip(self):
        res = binderview.Resolution(
            answers=[binderview.Answer("a.b.us", "A", 30, "10.0.0.1")],
            additionals=[
                binderview.Answer("h.a.b.us", "A", 60, "10.0.0.2")
            ],
        )
        out = decode_resolution(encode_resolution(res))
        assert [str(a) for a in out.answers] == [str(a) for a in res.answers]
        assert [str(a) for a in out.additionals] == [
            str(a) for a in res.additionals
        ]

    def test_resolve_request_name_extraction(self):
        body = pack_resolve("MyDomain.Example.US", "SRV", live=True)
        assert resolve_name(body) == "MyDomain.Example.US"
        assert body[0] & 1  # live flag
        frame = pack_frame(7, OP_RESOLVE, body)
        assert int.from_bytes(frame[:4], "big") == len(frame) - 4


# ---------------------------------------------------------------------------
# In-process worker: protocol ops, warm set, stale-while-unreachable
# ---------------------------------------------------------------------------


REG = {
    "domain": "one.shardtest.joyent.us",
    "type": "load_balancer",
    "service": {
        "type": "service",
        "service": {"srvce": "_http", "proto": "_tcp", "port": 80},
    },
}


def _worker_spec(server, path, shard=0):
    return {
        "socket": path,
        "shard": shard,
        "shards": 1,
        "servers": [[server.host, server.port]],
        "timeoutMs": 4000,
    }


async def test_worker_protocol_and_warm_set(tmp_path):
    server = await ZKServer().start()
    client = await ZKClient([server.address]).connect()
    worker = None
    chan = None
    try:
        await register(client, REG, admin_ip="10.6.0.1", hostname="h1",
                       settle_delay=0)
        worker = ShardWorker(
            _worker_spec(server, str(tmp_path / "w.sock"))
        )
        await worker.start()
        chan = await Channel.open(worker.socket_path)

        status, body = await chan.request(
            OP_RESOLVE, pack_resolve(REG["domain"], "A")
        )
        assert status == STATUS_OK
        res = decode_resolution(body)
        assert [a.data for a in res.answers] == ["10.6.0.1"]
        assert (REG["domain"], "A") in worker.warm

        # A second resolve is a cache hit in the worker's ZKCache.
        hits_before = worker.cache.stats["hits"]
        await chan.request(OP_RESOLVE, pack_resolve(REG["domain"], "A"))
        assert worker.cache.stats["hits"] > hits_before

        # STATUS carries the rollup fields the router aggregates.
        status, body = await chan.request(OP_STATUS, b"")
        st = json.loads(bytes(body).decode())
        assert st["resolves_total"] == 2
        assert st["session"]["connected"] is True
        assert st["authoritative"] is True

        # Unknown op answers an error frame, not a dead connection.
        status, body = await chan.request(99, b"")
        assert status == STATUS_ERR
        assert b"unknown op" in bytes(body)

        # The warm set is LRU-bounded by maxEntries.
        worker.max_entries = 2
        for i in range(4):
            worker._touch(f"d{i}.x.us", "A", b"{}")
        assert len(worker.warm) == 2
        assert ("d3.x.us", "A") in worker.warm
    finally:
        if chan is not None:
            await chan.close()
        if worker is not None:
            await worker.close()
        await client.close()
        await server.stop()


async def test_worker_stale_while_unreachable(tmp_path):
    """A transient backend outage serves the bounded-age last-known-good
    answer instead of failing the slice; an explicit live read still
    fails truthfully, and an expired record is not served."""
    server = await ZKServer().start()
    client = await ZKClient([server.address]).connect()
    worker = None
    chan = None
    try:
        await register(client, REG, admin_ip="10.6.0.1", hostname="h1",
                       settle_delay=0)
        worker = ShardWorker(
            _worker_spec(server, str(tmp_path / "w.sock"))
        )
        await worker.start()
        chan = await Channel.open(worker.socket_path)
        status, body = await chan.request(
            OP_RESOLVE, pack_resolve(REG["domain"], "A")
        )
        assert status == STATUS_OK
        warm_answer = bytes(body)

        await client.close()
        await server.stop()  # the whole backend goes away
        # Cached resolves fall back to the last-known-good bytes.
        deadline = time.monotonic() + 5
        while True:
            status, body = await chan.request(
                OP_RESOLVE, pack_resolve(REG["domain"], "A")
            )
            if status == STATUS_OK:
                break
            # The worker may still have been flushing its cache when the
            # first post-outage resolve arrived; it must settle into
            # stale serving, not erroring.
            assert time.monotonic() < deadline, bytes(body)
            await asyncio.sleep(0.05)
        assert bytes(body) == warm_answer
        assert worker.stale_serves >= 1

        # An explicit live read never serves stale.
        status, body = await chan.request(
            OP_RESOLVE, pack_resolve(REG["domain"], "A", live=True)
        )
        assert status == STATUS_ERR

        # Past the bound, the record is too old to lie about.
        worker.max_stale_s = 0.0
        await asyncio.sleep(0.01)
        status, body = await chan.request(
            OP_RESOLVE, pack_resolve(REG["domain"], "A")
        )
        assert status == STATUS_ERR
    finally:
        if chan is not None:
            await chan.close()
        if worker is not None:
            await worker.close()


# ---------------------------------------------------------------------------
# The full tier: parity, resharding, crash supervision
# ---------------------------------------------------------------------------


#: README-derived resolve scenarios (the test_binderview shapes): a
#: service fleet (A + SRV), a direct host record, an alias, an absent
#: domain — sharded-vs-single parity must hold across all of them
def _parity_registrations():
    return [
        (
            {
                "domain": "web.parity.joyent.us",
                "type": "load_balancer",
                "aliases": ["alias.web.parity.joyent.us"],
                "service": {
                    "type": "service",
                    "service": {
                        "srvce": "_http", "proto": "_tcp", "port": 80,
                    },
                },
            },
            "10.77.0.%d",
            3,
        ),
        (
            {"domain": "lonely.parity.joyent.us", "type": "host"},
            "10.78.0.%d",
            1,
        ),
    ]


_PARITY_QUERIES = (
    ("web.parity.joyent.us", "A"),
    ("_http._tcp.web.parity.joyent.us", "SRV"),
    ("alias.web.parity.joyent.us", "A"),
    ("lonely.parity.joyent.us", "A"),
    ("absent.parity.joyent.us", "A"),
)


async def test_sharded_vs_single_cache_parity(tmp_path):
    """The tier must answer byte-for-byte what an in-process resolve
    over a plain client answers, for every README scenario shape —
    through the router relay AND the direct data plane."""
    server = await ZKServer().start()
    clients = []
    router = None
    sc = dc = None
    try:
        for reg, ip_fmt, instances in _parity_registrations():
            for i in range(instances):
                cl = await ZKClient([server.address]).connect()
                clients.append(cl)
                await register(
                    cl, reg, admin_ip=ip_fmt % i, hostname=f"i{i}",
                    settle_delay=0,
                )
        observer = await ZKClient([server.address]).connect()
        clients.append(observer)
        router = await ShardRouter(
            [server.address], 2, str(tmp_path / "parity.sock"),
            attach_spread="any",
        ).start()
        sc = await ShardClient(router.socket_path).connect()
        dc = await ShardDirectClient(router.socket_path).connect()
        for name, qtype in _PARITY_QUERIES:
            expected = await binderview.resolve(observer, name, qtype)
            for res in (
                await sc.resolve(name, qtype),
                await dc.resolve(name, qtype),
                await sc.resolve(name, qtype, live=True),
            ):
                assert [str(a) for a in res.answers] == [
                    str(a) for a in expected.answers
                ], (name, qtype)
                assert [str(a) for a in res.additionals] == [
                    str(a) for a in expected.additionals
                ], (name, qtype)
    finally:
        if sc is not None:
            await sc.close()
        if dc is not None:
            await dc.close()
        if router is not None:
            await router.stop()
        for cl in clients:
            await cl.close()
        await server.stop()


async def test_reshard_bounded_movement_zero_errors(tmp_path):
    """Resharding 2 -> 3 mid-traffic: a 10 ms-poll resolver sees ZERO
    errors, the warm handoff moves only domains whose owner changed
    (<= ceil(K/N) + slack of the K warm domains), and the moved slice
    answers warm from its new owner."""
    server = await ZKServer().start()
    client = await ZKClient([server.address]).connect()
    router = None
    sc = None
    try:
        domains = []
        for i in range(12):
            dom = f"svc{i}.reshard.joyent.us"
            await register(
                client,
                {
                    "domain": dom,
                    "type": "load_balancer",
                    "service": {
                        "type": "service",
                        "service": {
                            "srvce": "_http", "proto": "_tcp", "port": 80,
                        },
                    },
                },
                admin_ip=f"10.9.0.{i}", hostname="h0", settle_delay=0,
            )
            domains.append(dom)
        router = await ShardRouter(
            [server.address], 2, str(tmp_path / "reshard.sock"),
            attach_spread="any",
        ).start()
        sc = await ShardClient(router.socket_path).connect()
        for dom in domains:  # warm every domain into the tier
            res = await sc.resolve(dom, "A")
            assert res.answers

        old_ring = router.ring
        polling = True
        errors = []

        async def poll():
            polled = 0
            while polling:
                for dom in domains:
                    try:
                        res = await sc.resolve(dom, "A")
                        if not res.answers:
                            errors.append(f"{dom}: empty")
                    except Exception as err:  # noqa: BLE001 - the tally IS the assertion
                        errors.append(f"{dom}: {err!r}")
                    polled += 1
                await asyncio.sleep(0.01)
            return polled

        poller = asyncio.ensure_future(poll())
        outcome = await router.reshard(3)
        await asyncio.sleep(0.05)
        polling = False
        polled = await poller
        assert polled > 0
        assert errors == [], errors[:5]

        # Movement bound over the tier's warm set (12 domains + the
        # negative/odd paths the warm set may carry).
        k = len(domains)
        moved_domains = old_ring.moved(router.ring, domains)
        assert len(moved_domains) <= -(-k // 2) + k // 4 + 1
        assert outcome["moved"] >= len(moved_domains)
        assert outcome["shards"] == 3
        assert router.generation == 1

        # The moved domains answer from their NEW owner's warm set: its
        # worker pre-resolved them before the flip.
        st = await router.status()
        warm_total = sum(
            info["warm"] for info in st["shards"].values()
        )
        assert warm_total >= k

        # No-op reshard moves nothing.
        assert (await router.reshard(3))["moved"] == 0
    finally:
        if sc is not None:
            await sc.close()
        if router is not None:
            await router.stop()
        await client.close()
        await server.stop()


async def test_worker_crash_respawn_e2e(tmp_path):
    """SIGKILL one worker under a 10 ms-poll resolver: the surviving
    shards' slices answer with ZERO errors throughout, the dead slice
    recovers within the respawn bound, and the router's status/metrics
    record the crash."""
    from registrar_tpu import metrics as metrics_mod

    server = await ZKServer().start()
    client = await ZKClient([server.address]).connect()
    router = None
    sc = None
    try:
        domains = []
        for i in range(8):
            dom = f"svc{i}.crash.joyent.us"
            await register(
                client,
                {
                    "domain": dom,
                    "type": "load_balancer",
                    "service": {
                        "type": "service",
                        "service": {
                            "srvce": "_http", "proto": "_tcp", "port": 80,
                        },
                    },
                },
                admin_ip=f"10.10.0.{i}", hostname="h0", settle_delay=0,
            )
            domains.append(dom)
        router = await ShardRouter(
            [server.address], 2, str(tmp_path / "crash.sock"),
            attach_spread="any", poll_interval_s=0.2,
        ).start()
        registry = metrics_mod.instrument_shards(router)
        sc = await ShardClient(router.socket_path).connect()
        for dom in domains:
            assert (await sc.resolve(dom, "A")).answers

        victim = router.ring.owner(domains[0])
        victim_doms = [
            d for d in domains if router.ring.owner(d) == victim
        ]
        surviving = [d for d in domains if d not in victim_doms]
        assert surviving, "sample too small to cover both shards"

        surviving_errors = []
        victim_recovered_at = None
        polling = True

        async def poll():
            nonlocal victim_recovered_at
            while polling:
                for dom in surviving:
                    try:
                        res = await sc.resolve(dom, "A")
                        if not res.answers:
                            surviving_errors.append(f"{dom}: empty")
                    except Exception as err:  # noqa: BLE001 - the tally IS the assertion
                        surviving_errors.append(f"{dom}: {err!r}")
                if victim_recovered_at is None:
                    try:
                        if (await sc.resolve(victim_doms[0], "A")).answers:
                            victim_recovered_at = time.monotonic()
                    except Exception:  # noqa: BLE001 - still down
                        pass
                await asyncio.sleep(0.01)

        poller = asyncio.ensure_future(poll())
        await asyncio.sleep(0.1)  # healthy polls on both slices first
        killed_at = time.monotonic()
        router.kill_worker(victim)
        victim_recovered_at = None  # only post-kill recovery counts
        deadline = killed_at + 20
        while victim_recovered_at is None and time.monotonic() < deadline:
            await asyncio.sleep(0.02)
        polling = False
        await poller

        assert victim_recovered_at is not None, "victim slice never recovered"
        assert surviving_errors == [], surviving_errors[:5]

        st = await router.status()
        assert st["serve"]["respawns_total"] == 1
        assert st["shards"][str(victim)]["respawns"] == 1
        assert not st["degraded"]
        # metrics rollup saw the respawn; resolves_total stayed monotonic
        respawns = registry.get("registrar_shard_respawns_total")
        assert respawns.value({"shard": str(victim)}) == 1.0
    finally:
        if sc is not None:
            await sc.close()
        if router is not None:
            await router.stop()
        await client.close()
        await server.stop()


async def test_router_degraded_without_respawn(tmp_path):
    """respawn_enabled=False (the SLO harness's repair-off mode): the
    dead shard stays down, status reports degraded, siblings keep
    serving."""
    server = await ZKServer().start()
    client = await ZKClient([server.address]).connect()
    router = None
    sc = None
    try:
        await register(client, REG, admin_ip="10.6.0.1", hostname="h1",
                       settle_delay=0)
        router = await ShardRouter(
            [server.address], 2, str(tmp_path / "down.sock"),
            attach_spread="any",
        ).start()
        router.respawn_enabled = False
        sc = await ShardClient(router.socket_path).connect()
        victim = router.ring.owner(REG["domain"])
        sibling = 1 - victim
        router.kill_worker(victim)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            st = await router.status()
            if st["degraded"]:
                break
            await asyncio.sleep(0.05)
        st = await router.status()
        assert st["degraded"] and st["shards_down"] == [victim]
        with pytest.raises(ShardError):
            await sc.resolve(REG["domain"], "A")
        # the sibling's slice still answers (any warm/fillable domain
        # it owns — ownership is a hint, workers answer anything)
        ring = router.ring
        for i in range(64):
            name = f"probe{i}.crash.joyent.us"
            if ring.owner(name) == sibling:
                res = await sc.resolve(name, "A")
                assert res.empty  # absent domain: clean empty, no error
                break
        else:
            pytest.fail("no sibling-owned probe name found")
    finally:
        if sc is not None:
            await sc.close()
        if router is not None:
            await router.stop()
        await client.close()
        await server.stop()
