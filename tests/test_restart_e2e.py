"""Zero-downtime restart e2e (ISSUE 5): handoff, drain, and fallbacks.

The headline acceptance test: a resolver polling through an agent
restart in handoff mode observes ZERO NO_NODE answers — the successor
process reattaches the predecessor's ZooKeeper session from the state
file and verifies (not recreates) the registration.  Drain mode's
bounded gap, the second-signal escape hatch, the SIGHUP hot reload, and
every degraded statefile shape (stale stamp, passwd tamper, config-hash
mismatch, expired reattach — each must land in a clean fresh-session
registration) are pinned alongside.

In-process tests drive ``main.run`` directly against the testing server
(signals delivered to our own pid — the loop's handlers catch them);
subprocess tests run the real daemon for the exit-code/relaunch shapes.
`make restart-e2e` runs this module in CI's chaos job.
"""

import asyncio
import json
import os
import signal
import socket
import subprocess
import sys
import time

from registrar_tpu import statefile
from registrar_tpu.config import parse_config
from registrar_tpu.main import EX_FORCED, run
from registrar_tpu.statefile import SessionState
from registrar_tpu.testing.server import ZKServer
from registrar_tpu.zk.client import ZKClient

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HOSTNAME = socket.gethostname()
DOMAIN = "ho.e2e.registrar"
PATH = "/registrar/e2e/ho"
NODE = f"{PATH}/{HOSTNAME}"


def _cfg_dict(server, state_file, mode="handoff", grace=0, **over):
    cfg = {
        "registration": {
            "domain": DOMAIN,
            "type": "load_balancer",
            "heartbeatInterval": 100,
        },
        "adminIp": "10.66.77.88",
        "zookeeper": {
            "servers": [{"host": server.host, "port": server.port}],
            "timeout": 10000,
        },
        "restart": {
            "stateFile": str(state_file),
            "mode": mode,
            "drainGraceSeconds": grace,
        },
    }
    cfg.update(over)
    return cfg


class _Poller:
    """Existence poller standing in for a Binder resolver: every tick it
    asks "is the host record there?" and records each NO_NODE answer."""

    def __init__(self, observer, node):
        self.observer = observer
        self.node = node
        self.misses = 0
        self.checks = 0
        self.owners = set()
        self._stop = asyncio.Event()
        self._task = None

    def start(self):
        self._task = asyncio.create_task(self._loop())
        return self

    async def _loop(self):
        while not self._stop.is_set():
            st = await self.observer.exists(self.node)
            self.checks += 1
            if st is None:
                self.misses += 1
            else:
                self.owners.add(st.ephemeral_owner)
            await asyncio.sleep(0.01)

    async def stop(self):
        self._stop.set()
        if self._task is not None:
            await self._task


async def _wait_for(pred, timeout=20, interval=0.05):
    deadline = asyncio.get_running_loop().time() + timeout
    while True:
        result = await pred()
        if result:
            return result
        assert asyncio.get_running_loop().time() < deadline, "timed out"
        await asyncio.sleep(interval)


class TestHandoffInProcess:
    async def test_sigterm_handoff_then_resume_zero_no_node_window(
        self, tmp_path
    ):
        # THE tentpole behavior, in-process: SIGTERM persists the state
        # and detaches; the successor reattaches the same session and
        # verifies in place; the observer never once sees the node gone.
        server = await ZKServer(max_session_timeout_ms=30000).start()
        observer = await ZKClient([server.address]).connect()
        state_path = tmp_path / "state.json"
        cfg = parse_config(_cfg_dict(server, state_path))
        task2 = None
        try:
            task1 = asyncio.create_task(run(cfg, _exit=lambda c: None))
            await _wait_for(lambda: observer.exists(NODE))
            sid0 = (await observer.stat(NODE)).ephemeral_owner
            assert sid0 != 0

            poller = _Poller(observer, NODE).start()
            os.kill(os.getpid(), signal.SIGTERM)
            await asyncio.wait_for(task1, timeout=15)

            # predecessor is gone, its statefile and ephemerals are not
            state = statefile.load(str(state_path))
            assert state.session_id == sid0
            assert NODE in state.znodes
            assert (await observer.stat(NODE)).ephemeral_owner == sid0
            stamp0 = state.stamp

            cfg2 = parse_config(_cfg_dict(server, state_path))
            task2 = asyncio.create_task(run(cfg2, _exit=lambda c: None))
            # the successor rewrites the statefile when it registers
            await _wait_for(
                lambda: asyncio.sleep(
                    0, statefile.load(str(state_path)).stamp != stamp0
                ),
                timeout=15,
            )
            await asyncio.sleep(0.3)  # a few heartbeats through the poller
            await poller.stop()

            assert poller.checks > 10
            assert poller.misses == 0, (
                f"resolver saw {poller.misses} NO_NODE answers across a "
                "handoff restart"
            )
            # ... and it was the SAME session the whole way through
            assert poller.owners == {sid0}
            assert (await observer.stat(NODE)).ephemeral_owner == sid0
        finally:
            if task2 is not None:
                task2.cancel()
                try:
                    await task2
                except asyncio.CancelledError:
                    pass
            await observer.close()
            await server.stop()

    async def test_drain_mode_unregisters_then_exits(self, tmp_path):
        server = await ZKServer(max_session_timeout_ms=30000).start()
        observer = await ZKClient([server.address]).connect()
        state_path = tmp_path / "state.json"
        cfg = parse_config(
            _cfg_dict(server, state_path, mode="drain", grace=0.3)
        )
        try:
            task = asyncio.create_task(run(cfg, _exit=lambda c: None))
            await _wait_for(lambda: observer.exists(NODE))
            t0 = asyncio.get_running_loop().time()
            os.kill(os.getpid(), signal.SIGTERM)
            # the node is deregistered promptly, not via session timeout
            await _wait_for(
                lambda: _absent(observer, NODE), timeout=10
            )
            await asyncio.wait_for(task, timeout=15)
            elapsed = asyncio.get_running_loop().time() - t0
            # ...and the exit respected drainGraceSeconds
            assert elapsed >= 0.3
            # a drained session has nothing to hand off
            assert not state_path.exists()
        finally:
            await observer.close()
            await server.stop()


async def _absent(observer, node):
    return await observer.exists(node) is None


class TestDrainResilience:
    async def test_drain_continues_past_an_already_absent_node(
        self, tmp_path
    ):
        # REVIEW FIX: the drain walk must not abort on the first NO_NODE
        # (a node deleted out-of-band) — every remaining LIVE record has
        # to leave DNS before the process exits.
        server = await ZKServer(max_session_timeout_ms=30000).start()
        observer = await ZKClient([server.address]).connect()
        reg = {
            "domain": DOMAIN,
            "type": "load_balancer",
            "aliases": ["two.e2e.registrar"],
            "heartbeatInterval": 60000,  # no repair racing the test
        }
        cfg = parse_config(_cfg_dict(
            server, tmp_path / "state.json", mode="drain",
            registration=reg,
        ))
        alias_node = "/registrar/e2e/two"
        try:
            task = asyncio.create_task(run(cfg, _exit=lambda c: None))
            await _wait_for(lambda: observer.exists(NODE))
            await _wait_for(lambda: observer.exists(alias_node))
            # the FIRST node in the owned list vanishes out-of-band
            await observer.unlink(NODE)
            os.kill(os.getpid(), signal.SIGTERM)
            await asyncio.wait_for(task, timeout=15)
            # the walk kept going: the alias left DNS too
            assert await observer.exists(alias_node) is None
        finally:
            await observer.close()
            await server.stop()


class TestResumeFallbacks:
    """Every degraded statefile shape lands in a clean fresh-session
    registration (the acceptance list, one test per branch)."""

    async def _run_and_expect_fresh(self, server, cfg, not_owner):
        observer = await ZKClient([server.address]).connect()
        task = asyncio.create_task(run(cfg, _exit=lambda c: None))
        try:
            await _wait_for(lambda: observer.exists(NODE))
            st = await observer.stat(NODE)
            assert st.ephemeral_owner != 0
            assert st.ephemeral_owner != not_owner
            data, _ = await observer.get(NODE)
            rec = json.loads(data)
            assert rec["load_balancer"]["address"] == "10.66.77.88"
            assert not task.done(), "daemon died instead of falling back"
        finally:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
            await observer.close()

    def _fingerprint(self, cfg):
        return statefile.config_fingerprint(
            cfg.registration, cfg.admin_ip, cfg.zookeeper.chroot
        )

    def _state(self, cfg, **over):
        base = dict(
            session_id=0xDEAD1234,
            passwd=b"\x05" * 16,
            negotiated_timeout_ms=10000,
            last_zxid=0,
            chroot="",
            config_hash=self._fingerprint(cfg),
            znodes=[NODE],
            pid=99999,
            stamp=time.time(),
        )
        base.update(over)
        return SessionState(**base)

    async def test_stale_stamp_falls_back_fresh(self, tmp_path):
        server = await ZKServer().start()
        try:
            state_path = tmp_path / "state.json"
            cfg = parse_config(_cfg_dict(server, state_path))
            statefile.save(
                str(state_path),
                self._state(cfg, stamp=time.time() - 60.0),
            )
            await self._run_and_expect_fresh(server, cfg, 0xDEAD1234)
        finally:
            await server.stop()

    async def test_config_hash_mismatch_falls_back_fresh(self, tmp_path):
        server = await ZKServer().start()
        try:
            state_path = tmp_path / "state.json"
            cfg = parse_config(_cfg_dict(server, state_path))
            statefile.save(
                str(state_path),
                self._state(cfg, config_hash="not-this-config"),
            )
            await self._run_and_expect_fresh(server, cfg, 0xDEAD1234)
        finally:
            await server.stop()

    async def test_tampered_passwd_falls_back_fresh(self, tmp_path):
        server = await ZKServer().start()
        try:
            state_path = tmp_path / "state.json"
            cfg = parse_config(_cfg_dict(server, state_path))
            statefile.save(str(state_path), self._state(cfg))
            raw = json.loads(state_path.read_text())
            raw["passwd"] = "c2hvcnQ="  # "short": not 16 bytes
            state_path.write_text(json.dumps(raw))
            await self._run_and_expect_fresh(server, cfg, 0xDEAD1234)
        finally:
            await server.stop()

    async def test_foreign_file_falls_back_fresh(self, tmp_path):
        server = await ZKServer().start()
        try:
            state_path = tmp_path / "state.json"
            state_path.write_text('{"something": "else entirely"}')
            cfg = parse_config(_cfg_dict(server, state_path))
            await self._run_and_expect_fresh(server, cfg, 0xDEAD1234)
        finally:
            await server.stop()

    async def test_expired_session_reattach_refused_falls_back_fresh(
        self, tmp_path
    ):
        # The statefile is perfectly valid — but the session it names
        # died in the gap.  The server's refusal must degrade to a fresh
        # session + registration, never to the terminal session_expired.
        server = await ZKServer().start()
        try:
            state_path = tmp_path / "state.json"
            cfg = parse_config(_cfg_dict(server, state_path))
            pre = await ZKClient(
                [server.address], timeout_ms=10000
            ).connect()
            sid = pre.session_id
            statefile.save(
                str(state_path),
                self._state(
                    cfg,
                    session_id=sid,
                    passwd=pre.session_passwd,
                    negotiated_timeout_ms=pre.negotiated_timeout_ms,
                ),
            )
            await pre.close()  # the session is gone server-side
            await self._run_and_expect_fresh(server, cfg, sid)
        finally:
            await server.stop()


def _spawn_daemon(cfg_path, stdout=subprocess.PIPE):
    return subprocess.Popen(
        [sys.executable, "-m", "registrar_tpu", "-f", str(cfg_path)],
        cwd=REPO, stdout=stdout, stderr=subprocess.STDOUT,
        env={**os.environ, "PYTHONPATH": REPO, "LOG_LEVEL": "info"},
    )


class TestHandoffSubprocess:
    async def test_real_daemon_restart_has_zero_no_node_window(
        self, tmp_path
    ):
        # The ISSUE's headline, with the real daemon binary: resolver
        # polls through SIGTERM + relaunch; zero NO_NODE, same session,
        # and the successor's reconciler sweeps report zero drift.
        server = await ZKServer(max_session_timeout_ms=30000).start()
        observer = await ZKClient([server.address]).connect()
        state_path = tmp_path / "state.json"
        cfg_path = tmp_path / "config.json"
        cfg_path.write_text(json.dumps(_cfg_dict(
            server, state_path,
            reconcile={"intervalSeconds": 0.2, "repair": True},
        )))
        proc = succ = None
        try:
            proc = _spawn_daemon(cfg_path)
            await _wait_for(lambda: observer.exists(NODE))
            sid0 = (await observer.stat(NODE)).ephemeral_owner

            poller = _Poller(observer, NODE).start()
            proc.send_signal(signal.SIGTERM)
            rc = await asyncio.to_thread(proc.wait, 15)
            assert rc == 0, proc.stdout.read().decode()
            pred_out = proc.stdout.read().decode()
            assert "session handed off" in pred_out

            state = statefile.load(str(state_path))
            assert state.session_id == sid0
            stamp0 = state.stamp

            succ = _spawn_daemon(cfg_path)
            await _wait_for(
                lambda: asyncio.sleep(
                    0, statefile.load(str(state_path)).stamp != stamp0
                ),
                timeout=20,
            )
            # let the reconciler run a few post-resume sweeps
            await asyncio.sleep(0.8)
            await poller.stop()

            assert poller.misses == 0, (
                f"{poller.misses}/{poller.checks} polls saw NO_NODE"
            )
            assert poller.owners == {sid0}
            assert succ.poll() is None

            # stop the successor and read its log: it resumed (did not
            # re-register) and its sweeps found nothing to repair
            succ.send_signal(signal.SIGTERM)
            assert await asyncio.to_thread(succ.wait, 15) == 0
            out = succ.stdout.read().decode()
            assert "session resumed; verifying registration in place" in out
            assert "resumed registration verified in place" in out
            assert "drift detected" not in out
            assert "registrar: registered" in out  # the adopted set
        finally:
            for p in (proc, succ):
                if p is not None and p.poll() is None:
                    p.kill()
                    p.wait(timeout=10)
                if p is not None and p.stdout:
                    p.stdout.close()
            await observer.close()
            await server.stop()

    async def test_drain_mode_bounded_gap_and_clean_exit(self, tmp_path):
        server = await ZKServer(max_session_timeout_ms=30000).start()
        observer = await ZKClient([server.address]).connect()
        state_path = tmp_path / "state.json"
        cfg_path = tmp_path / "config.json"
        cfg_path.write_text(json.dumps(
            _cfg_dict(server, state_path, mode="drain", grace=0.2)
        ))
        proc = succ = None
        try:
            proc = _spawn_daemon(cfg_path, stdout=subprocess.DEVNULL)
            await _wait_for(lambda: observer.exists(NODE))
            proc.send_signal(signal.SIGTERM)
            rc = await asyncio.to_thread(proc.wait, 15)
            assert rc == 0
            # drained: the node left DNS immediately, not via timeout
            assert await observer.exists(NODE) is None
            assert not state_path.exists()

            # relaunch: the gap is bounded by a normal fresh
            # registration (connect + pipeline + 1 s settle)
            t0 = asyncio.get_running_loop().time()
            succ = _spawn_daemon(cfg_path, stdout=subprocess.DEVNULL)
            await _wait_for(lambda: observer.exists(NODE), timeout=20)
            gap = asyncio.get_running_loop().time() - t0
            assert gap < 15
        finally:
            for p in (proc, succ):
                if p is not None and p.poll() is None:
                    p.kill()
                    p.wait(timeout=10)
            await observer.close()
            await server.stop()

    async def test_second_signal_forces_immediate_exit(self, tmp_path):
        # Escape hatch: a graceful stop stuck in a 30 s drain grace gets
        # a second SIGTERM → immediate exit, distinct code + log line.
        server = await ZKServer(max_session_timeout_ms=30000).start()
        observer = await ZKClient([server.address]).connect()
        state_path = tmp_path / "state.json"
        cfg_path = tmp_path / "config.json"
        cfg_path.write_text(json.dumps(
            _cfg_dict(server, state_path, mode="drain", grace=30)
        ))
        proc = None
        try:
            proc = _spawn_daemon(cfg_path)
            await _wait_for(lambda: observer.exists(NODE))
            proc.send_signal(signal.SIGTERM)
            # wait until the drain actually ran (node deregistered) so
            # the second signal lands INSIDE the wedged grace period
            await _wait_for(lambda: _absent(observer, NODE), timeout=10)
            proc.send_signal(signal.SIGTERM)
            rc = await asyncio.to_thread(proc.wait, 10)
            assert rc == EX_FORCED
            out = proc.stdout.read().decode()
            assert "forcing immediate exit" in out
        finally:
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)
            if proc is not None and proc.stdout:
                proc.stdout.close()
            await observer.close()
            await server.stop()


class TestSighupReload:
    async def test_sighup_applies_registration_delta_in_place(
        self, tmp_path
    ):
        server = await ZKServer(max_session_timeout_ms=30000).start()
        observer = await ZKClient([server.address]).connect()
        cfg_path = tmp_path / "config.json"

        def write_cfg(aliases):
            cfg = {
                "registration": {
                    "domain": DOMAIN,
                    "type": "load_balancer",
                    "aliases": aliases,
                    "heartbeatInterval": 100,
                },
                "adminIp": "10.66.77.88",
                "zookeeper": {
                    "servers": [
                        {"host": server.host, "port": server.port}
                    ],
                    "timeout": 10000,
                },
            }
            cfg_path.write_text(json.dumps(cfg))

        alias1 = "/registrar/e2e/one"
        alias2 = "/registrar/e2e/two"
        write_cfg(["one.e2e.registrar"])
        proc = None
        try:
            proc = _spawn_daemon(cfg_path)
            await _wait_for(lambda: observer.exists(NODE))
            await _wait_for(lambda: observer.exists(alias1))
            host_before = await observer.stat(NODE)
            alias1_before = await observer.stat(alias1)

            # add an alias: only the new node is written
            write_cfg(["one.e2e.registrar", "two.e2e.registrar"])
            proc.send_signal(signal.SIGHUP)
            await _wait_for(lambda: observer.exists(alias2), timeout=15)
            host_mid = await observer.stat(NODE)
            alias1_mid = await observer.stat(alias1)
            assert (host_mid.czxid, host_mid.mzxid) == (
                host_before.czxid, host_before.mzxid
            )
            assert (alias1_mid.czxid, alias1_mid.mzxid) == (
                alias1_before.czxid, alias1_before.mzxid
            )

            # remove the first alias: only it is deleted
            write_cfg(["two.e2e.registrar"])
            proc.send_signal(signal.SIGHUP)
            await _wait_for(lambda: _absent(observer, alias1), timeout=15)
            assert await observer.exists(alias2) is not None
            host_after = await observer.stat(NODE)
            assert host_after.czxid == host_before.czxid

            # an invalid config must be rejected with the old one kept
            cfg_path.write_text("{ not json")
            proc.send_signal(signal.SIGHUP)
            await asyncio.sleep(0.5)
            assert proc.poll() is None
            assert await observer.exists(NODE) is not None
            assert await observer.exists(alias2) is not None

            proc.send_signal(signal.SIGTERM)
            assert await asyncio.to_thread(proc.wait, 15) == 0
            out = proc.stdout.read().decode()
            assert out.count("configuration reload applied") >= 2
            assert "invalid configuration" in out
        finally:
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)
            if proc is not None and proc.stdout:
                proc.stdout.close()
            await observer.close()
            await server.stop()
