"""Cross-process trace assembly (ISSUE 13): traceview's tree builder
and renderer, the wire-context propagation primitives in trace.py, the
health-check env stamps, and GET /debug/trace?id= on the metrics
listener.  The shard protocol's end of the feature (wire parity,
adoption, OP_TRACE collection) lives in tests/test_shard.py.
"""

import asyncio
import json

from registrar_tpu import trace, traceview


def _span(
    name,
    span_id,
    parent_id=None,
    trace_id="aa" * 8,
    t=1.0,
    duration_ms=1.0,
    **extra,
):
    return {
        "kind": "span",
        "name": name,
        "trace_id": trace_id,
        "span_id": span_id,
        "parent_id": parent_id,
        "time": t,
        "duration_ms": duration_ms,
        "status": "ok",
        "attrs": {},
        "marks": {},
        **extra,
    }


class TestAssemble:
    def test_parent_tree_across_fragments(self):
        # Three "processes" dumped separately: the caller's root, the
        # router's relay, the worker's resolve+zk.op — one tree.
        entries = [
            _span("slo.probe", "s1", None, t=1.0),
            _span("shard.relay", "s2", "s1", t=1.1, proc="router"),
            _span("resolve.query", "s3", "s2", t=1.2, proc="shard0"),
            _span("zk.op", "s4", "s3", t=1.3, proc="shard0"),
            _span("zk.op", "s5", "s3", t=1.25, proc="shard0"),
        ]
        tree = traceview.assemble(entries, "aa" * 8)
        assert tree["spans"] == 5
        assert tree["orphans"] == 0
        assert len(tree["roots"]) == 1
        root = tree["roots"][0]
        assert root["name"] == "slo.probe"
        relay = root["children"][0]
        assert relay["name"] == "shard.relay"
        resolve = relay["children"][0]
        assert resolve["name"] == "resolve.query"
        # children are time-ordered
        assert [c["span_id"] for c in resolve["children"]] == ["s5", "s4"]

    def test_other_traces_and_duplicates_excluded(self):
        entries = [
            _span("a", "s1", None),
            _span("a-dup", "s1", None),  # same span id: first wins
            _span("other", "x1", None, trace_id="bb" * 8),
        ]
        tree = traceview.assemble(entries, "aa" * 8)
        assert tree["spans"] == 1
        assert tree["roots"][0]["name"] == "a"

    def test_orphans_attach_under_missing_parent(self):
        # The parent lived in a process that crashed before handing its
        # fragment over: the surviving subtree must NOT vanish.
        entries = [
            _span("resolve.query", "s3", "gone", t=1.0),
            _span("zk.op", "s4", "s3", t=1.1),
        ]
        tree = traceview.assemble(entries, "aa" * 8)
        assert tree["orphans"] == 1
        assert tree["roots"][-1]["name"] == traceview.MISSING_PARENT
        assert tree["roots"][-1]["synthetic"] is True
        orphan = tree["roots"][-1]["children"][0]
        assert orphan["name"] == "resolve.query"
        # ...and its own child still chains normally beneath it.
        assert orphan["children"][0]["name"] == "zk.op"

    def test_events_ride_along_in_time_order(self):
        entries = [
            _span("a", "s1", None),
            {"kind": "event", "name": "slo.fault", "time": 2.0,
             "trace_id": "aa" * 8, "attrs": {"fault": "shard-kill"}},
            {"kind": "event", "name": "cache.invalidated", "time": 1.0,
             "trace_id": "aa" * 8, "attrs": {}},
            {"kind": "event", "name": "foreign", "time": 1.5,
             "trace_id": "bb" * 8, "attrs": {}},
        ]
        tree = traceview.assemble(entries, "aa" * 8)
        assert tree["events"] == 2
        assert [e["name"] for e in tree["events_list"]] == [
            "cache.invalidated", "slo.fault",
        ]

    def test_render_text_shows_structure_and_orphans(self):
        entries = [
            _span("slo.probe", "s1", None, t=1.0, duration_ms=5.5),
            _span(
                "shard.relay", "s2", "s1", t=1.1, proc="router",
                marks={"forwarded": 0.1, "worker": 1.2},
            ),
            _span("resolve.query", "s9", "gone", t=1.2, proc="shard1"),
        ]
        text = traceview.render_text(traceview.assemble(entries, "aa" * 8))
        assert "slo.probe  5.500ms  [ok]" in text
        assert "@router" in text
        assert "forwarded=0.1ms" in text and "worker=1.2ms" in text
        assert traceview.MISSING_PARENT in text
        # indentation: the relay is one level under the probe
        probe_line = next(l for l in text.splitlines() if "slo.probe" in l)
        relay_line = next(l for l in text.splitlines() if "shard.relay" in l)
        assert len(relay_line) - len(relay_line.lstrip()) > (
            len(probe_line) - len(probe_line.lstrip())
        )

    def test_worst_span_ms(self):
        entries = [
            _span("a", "s1", None, duration_ms=2.0),
            _span("b", "s2", "s1", duration_ms=7.25),
        ]
        tree = traceview.assemble(entries, "aa" * 8)
        assert traceview.worst_span_ms(tree) == 7.25
        assert traceview.worst_span_ms(
            traceview.assemble([], "aa" * 8)
        ) is None


class TestWireContext:
    """trace.current_context() + Tracer.adopt(): the propagation
    primitives every cross-process boundary rides."""

    def test_no_active_span_is_none(self):
        assert trace.current_context() is None

    def test_noop_span_carries_no_context(self):
        with trace.DISABLED.span("resolve.query"):
            assert trace.current_context() is None

    def test_context_round_trips_through_adopt(self):
        t = trace.Tracer(sample_rate=1.0)
        with t.span("slo.probe") as root:
            ctx = trace.current_context()
        assert ctx == (int(root.trace_id, 16), int(root.span_id, 16), 1)
        # "Another process": a fresh tracer adopting the triple.
        remote = trace.Tracer(sample_rate=1.0)
        with remote.adopt(*ctx):
            with remote.span("resolve.query") as child:
                pass
        assert child.trace_id == root.trace_id
        assert child.parent_id == root.span_id
        assert child.sampled is True
        # ...and the two recorders' fragments assemble into one tree.
        entries = (
            t.dump(trace_id=root.trace_id)["entries"]
            + remote.dump(trace_id=root.trace_id)["entries"]
        )
        tree = traceview.assemble(entries, root.trace_id)
        assert tree["orphans"] == 0
        assert tree["roots"][0]["name"] == "slo.probe"
        assert tree["roots"][0]["children"][0]["name"] == "resolve.query"

    def test_unsampled_verdict_is_inherited_whole(self):
        t = trace.Tracer(sample_rate=1.0)
        with t.adopt(0x1234, 0x5678, 0):
            with t.span("resolve.query") as child:
                pass
        assert child.sampled is False
        assert child.trace_id == f"{0x1234:016x}"
        assert t.dump()["entries"] == []  # nothing recorded

    def test_adopted_parent_is_never_recorded_locally(self):
        t = trace.Tracer(sample_rate=1.0)
        with t.adopt(0x1, 0x2, 1):
            pass
        assert t.dump()["entries"] == []

    def test_dump_filters_by_trace_id(self):
        t = trace.Tracer(sample_rate=1.0)
        with t.span("a") as a:
            t.event("cache.invalidated", path="/x")
        with t.span("b") as b:
            pass
        only_a = t.dump(trace_id=a.trace_id)["entries"]
        assert {e["name"] for e in only_a} == {"a", "cache.invalidated"}
        assert all(e["trace_id"] == a.trace_id for e in only_a)
        assert {e["name"] for e in t.dump(trace_id=b.trace_id)["entries"]} == {
            "b"
        }

    def test_disabled_tracer_adopt_is_noop(self):
        with trace.DISABLED.adopt(0x1, 0x2, 1) as sp:
            assert sp is trace.NOOP_SPAN
            assert trace.current_context() is None


class TestHealthTraceEnv:
    """health.exec stamps REGISTRAR_TRACE_ID/REGISTRAR_SPAN_ID into the
    check command's environment (ISSUE 13) — and ONLY while traced."""

    async def test_env_stamped_while_traced(self, tmp_path):
        from registrar_tpu.health import HealthCheck

        out = tmp_path / "env.txt"
        hc = HealthCheck(
            command=(
                f'echo "$REGISTRAR_TRACE_ID $REGISTRAR_SPAN_ID" > {out}'
            ),
            interval=60, timeout=5,
        )
        hc.tracer = trace.Tracer(sample_rate=1.0)
        await hc.check_once()
        (span,) = [
            e for e in hc.tracer.dump()["entries"]
            if e["name"] == "health.exec"
        ]
        stamped_trace, stamped_span = out.read_text().split()
        assert stamped_trace == span["trace_id"]
        assert stamped_span == span["span_id"]

    async def test_env_untouched_when_tracing_off(self, tmp_path):
        from registrar_tpu.health import HealthCheck

        out = tmp_path / "env.txt"
        hc = HealthCheck(
            command=(
                f'echo "${{REGISTRAR_TRACE_ID-unset}}" > {out}'
            ),
            interval=60, timeout=5,
        )
        await hc.check_once()
        assert out.read_text().strip() == "unset"


class TestDebugTraceById:
    """GET /debug/trace?id= on the metrics listener: the assembled-tree
    endpoint (async provider), coexisting with the ?n= raw-ring view."""

    async def _get(self, port, path):
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        try:
            writer.write(f"GET {path} HTTP/1.0\r\n\r\n".encode())
            await writer.drain()
            raw = await asyncio.wait_for(reader.read(), timeout=5)
        finally:
            writer.close()
        head, _, body = raw.partition(b"\r\n\r\n")
        return head.split()[1].decode(), json.loads(body)

    async def test_id_routes_to_tree_provider(self):
        from registrar_tpu import metrics as metrics_mod

        t = trace.Tracer(sample_rate=1.0)
        with t.span("resolve.query") as sp:
            pass

        async def tree_provider(trace_id):
            return traceview.assemble(
                t.dump(trace_id=trace_id)["entries"], trace_id
            )

        server = metrics_mod.MetricsServer(
            metrics_mod.MetricsRegistry(),
            trace_provider=lambda n: t.dump(n),
            trace_tree_provider=tree_provider,
        )
        await server.start()
        try:
            status, tree = await self._get(
                server.port, f"/debug/trace?id={sp.trace_id}"
            )
            assert status == "200"
            assert tree["trace_id"] == sp.trace_id
            assert tree["spans"] == 1
            assert tree["roots"][0]["name"] == "resolve.query"
            # ?n= still serves the raw ring alongside
            status, ring = await self._get(server.port, "/debug/trace?n=5")
            assert status == "200"
            assert ring["enabled"] is True and ring["entries"]
        finally:
            await server.stop()

    async def test_provider_error_answers_json_not_500(self):
        from registrar_tpu import metrics as metrics_mod

        async def exploding(trace_id):
            raise RuntimeError("worker unreachable")

        server = metrics_mod.MetricsServer(
            metrics_mod.MetricsRegistry(),
            trace_tree_provider=exploding,
        )
        await server.start()
        try:
            status, payload = await self._get(
                server.port, "/debug/trace?id=deadbeef"
            )
            assert status == "200"
            assert "worker unreachable" in payload["error"]
        finally:
            await server.stop()


class TestZkcliTraceId:
    """zkcli trace --id renders the assembled tree off the listener."""

    async def test_trace_id_renders_tree(self, tmp_path, capsys):
        from registrar_tpu import metrics as metrics_mod
        from registrar_tpu.tools import zkcli as zkcli_mod

        t = trace.Tracer(sample_rate=1.0)
        with t.span("shard.relay", shard=1) as relay:
            with t.span("resolve.query", qtype="A"):
                pass

        async def tree_provider(trace_id):
            return traceview.assemble(
                t.dump(trace_id=trace_id)["entries"], trace_id
            )

        server = metrics_mod.MetricsServer(
            metrics_mod.MetricsRegistry(),
            trace_tree_provider=tree_provider,
        )
        await server.start()
        try:
            cfg = tmp_path / "cfg.json"
            cfg.write_text(json.dumps({
                "registration": {"domain": "a.b.c", "type": "host"},
                "zookeeper": {
                    "servers": [{"host": "127.0.0.1", "port": 1}]
                },
                "metrics": {"port": server.port},
            }))

            class Args:
                file = str(cfg)
                id = relay.trace_id
                json = False
                n = 200
                timeout = 5.0

            rc = await zkcli_mod._cmd_trace(Args())
            out = capsys.readouterr().out
            assert rc == 0
            assert relay.trace_id in out
            assert "shard.relay" in out and "resolve.query" in out

            # An unknown id exits 1 (nothing recorded), not 0.
            Args.id = "00" * 8
            assert await zkcli_mod._cmd_trace(Args()) == 1
        finally:
            await server.stop()
