"""Independent client-oracle interop: kazoo <-> this repo's ZK client.

Round-4 verdict #4: the golden wire frames and the hermetic server are
both authored by this repo, so they can only prove self-consistency.
kazoo — the de-facto Python ZooKeeper client, with its own independent
jute implementation — is an oracle this repo did not write.  Each test
here drives one side with kazoo and the other side with
``registrar_tpu.zk.client`` against a *real* ZooKeeper (the reference's
own test dependency, reference test/helper.js:57-62), so any wire-format
or semantics divergence surfaces as a byte-level mismatch.

Requires both a live ZooKeeper (``ZK_HOST``/``ZK_PORT``) and kazoo
installed; skipped otherwise.  The ``real-zk`` CI job provides both.
"""

import asyncio
import os
import threading
import uuid

import pytest

kazoo_client_mod = pytest.importorskip(
    "kazoo.client", reason="kazoo not installed (pip install kazoo)"
)
from kazoo.client import KazooClient  # noqa: E402

from registrar_tpu.records import parse_payload  # noqa: E402
from registrar_tpu.registration import register, unregister  # noqa: E402
from registrar_tpu.zk.client import Op, ZKClient  # noqa: E402
from registrar_tpu.zk.protocol import (  # noqa: E402
    Err,
    ZKError,
    creator_all_acl,
)

pytestmark = pytest.mark.skipif(
    not os.environ.get("ZK_HOST"),
    reason="set ZK_HOST (and optionally ZK_PORT) to run kazoo interop tests",
)


def _servers():
    return [(os.environ["ZK_HOST"], int(os.environ.get("ZK_PORT", "2181")))]


def _hosts_str():
    host, port = _servers()[0]
    return f"{host}:{port}"


@pytest.fixture
def kz():
    client = KazooClient(hosts=_hosts_str())
    client.start(timeout=20)
    yield client
    try:
        client.stop()
    finally:
        client.close()


class TestKazooInterop:
    async def test_kazoo_writes_our_client_reads(self, kz):
        base = f"/kazoo-interop-{uuid.uuid4().hex[:8]}"
        payload = b'{"written-by":"kazoo","n":1}'
        await asyncio.to_thread(kz.create, base, b"parent")
        await asyncio.to_thread(kz.create, f"{base}/eph", payload,
                                ephemeral=True)
        ours = await ZKClient(_servers()).connect()
        try:
            # Payload byte-equality through our decoder.
            data, stat = await ours.get(f"{base}/eph")
            assert data == payload
            # The ephemeral owner is kazoo's session, decoded by us.
            assert stat.ephemeral_owner == kz.client_id[0]
            assert await ours.get_children(base) == ["eph"]
            parent, pstat = await ours.get(base)
            assert parent == b"parent"
            assert pstat.ephemeral_owner == 0
        finally:
            await ours.close()
        await asyncio.to_thread(kz.delete, base, recursive=True)

    async def test_our_registration_read_by_kazoo(self, kz):
        # The full registration pipeline's znodes, read back through the
        # independent client: payloads byte-identical, ephemerals owned
        # by our session.
        domain = f"kz-{uuid.uuid4().hex[:8]}.interop.registrar"
        ours = await ZKClient(_servers()).connect()
        try:
            nodes = await register(
                ours,
                {
                    "domain": domain,
                    "type": "load_balancer",
                    "service": {
                        "type": "service",
                        "service": {
                            "srvce": "_http", "proto": "_tcp", "port": 80,
                        },
                    },
                },
                admin_ip="10.250.1.1",
                hostname="kazoohost",
                settle_delay=0.05,
            )
            for n in nodes:
                our_data, our_stat = await ours.get(n)
                kz_data, kz_stat = await asyncio.to_thread(kz.get, n)
                assert kz_data == our_data  # byte equality across clients
                assert kz_stat.ephemeralOwner == our_stat.ephemeral_owner
                assert kz_stat.mzxid == our_stat.mzxid
                payload = parse_payload(kz_data)
                assert payload["type"] in ("load_balancer", "service")
            await unregister(ours, nodes)
            for n in nodes:
                assert await asyncio.to_thread(kz.exists, n) is None
            # clean the persistent parent chain
            for p in sorted({n.rsplit("/", 1)[0] for n in nodes},
                            key=len, reverse=True):
                while p and p != "/":
                    try:
                        await ours.unlink(p)
                    except Exception:  # noqa: BLE001 - shared parents stay
                        break
                    p = p.rsplit("/", 1)[0]
        finally:
            await ours.close()

    async def test_watch_delivery_both_directions(self, kz):
        path = f"/kazoo-interop-watch-{uuid.uuid4().hex[:8]}"
        ours = await ZKClient(_servers()).connect()
        try:
            await ours.create(path, b"v0")

            # kazoo writes -> our watch fires.
            our_event = asyncio.Event()
            loop = asyncio.get_running_loop()
            ours.watch(
                path,
                lambda ev: loop.call_soon_threadsafe(our_event.set),
            )
            await ours.stat(path, watch=True)
            await asyncio.to_thread(kz.set, path, b"v1")
            await asyncio.wait_for(our_event.wait(), timeout=10)

            # our client writes -> kazoo's watch fires.
            kz_event = threading.Event()
            await asyncio.to_thread(
                kz.get, path, lambda ev: kz_event.set()
            )
            await ours.set_data(path, b"v2")
            assert await asyncio.to_thread(kz_event.wait, 10)

            await ours.unlink(path)
        finally:
            await ours.close()

    async def test_acl_round_trip_across_clients(self, kz):
        from kazoo.exceptions import NoAuthError
        from kazoo.security import make_digest_acl

        path = f"/kazoo-interop-acl-{uuid.uuid4().hex[:8]}"
        ours = await ZKClient(_servers()).connect()
        try:
            # Our digest formula must be accepted by real ZK *and* match
            # what kazoo computes for the same user:password.
            await ours.add_auth("digest", b"oracle:secret")
            await ours.create(
                path, b"locked", acls=creator_all_acl("oracle", "secret")
            )

            with pytest.raises(NoAuthError):
                await asyncio.to_thread(kz.get, path)

            await asyncio.to_thread(kz.add_auth, "digest", "oracle:secret")
            data, _ = await asyncio.to_thread(kz.get, path)
            assert data == b"locked"

            kz_acls, _ = await asyncio.to_thread(kz.get_acl, path)
            expected = make_digest_acl("oracle", "secret", all=True)
            assert len(kz_acls) == 1
            assert kz_acls[0].id == expected.id  # identical digest bytes
            assert kz_acls[0].perms == expected.perms

            # Reverse direction: kazoo-created ACL node, our auth reads.
            path2 = f"{path}-rev"
            await asyncio.to_thread(
                kz.create, path2, b"kz-locked",
                [make_digest_acl("oracle", "secret", all=True)],
            )
            stranger = await ZKClient(_servers()).connect()
            try:
                with pytest.raises(ZKError) as exc:
                    await stranger.get(path2)
                assert exc.value.code == Err.NO_AUTH
                await stranger.add_auth("digest", b"oracle:secret")
                assert (await stranger.get(path2))[0] == b"kz-locked"
                await stranger.unlink(path2)
            finally:
                await stranger.close()
            await ours.unlink(path)
        finally:
            await ours.close()

    async def test_multi_both_directions(self, kz):
        base = f"/kazoo-interop-multi-{uuid.uuid4().hex[:8]}"
        ours = await ZKClient(_servers()).connect()
        try:
            # Our multi, observed by kazoo.
            await ours.multi([
                Op.create(base, b""),
                Op.create(f"{base}/a", b"one"),
                Op.set_data(f"{base}/a", b"two"),
            ])
            data, _ = await asyncio.to_thread(kz.get, f"{base}/a")
            assert data == b"two"

            # kazoo's transaction, observed by us.
            def kz_txn():
                t = kz.transaction()
                t.create(f"{base}/b", b"three")
                t.set_data(f"{base}/a", b"four")
                return t.commit()

            results = await asyncio.to_thread(kz_txn)
            assert not any(isinstance(r, Exception) for r in results)
            assert (await ours.get(f"{base}/b"))[0] == b"three"
            assert (await ours.get(f"{base}/a"))[0] == b"four"

            await ours.multi([
                Op.delete(f"{base}/a"),
                Op.delete(f"{base}/b"),
                Op.delete(base),
            ])
            assert await asyncio.to_thread(kz.exists, base) is None
        finally:
            await ours.close()
