"""Binder-semantics resolution tests, pinned to the reference README's
worked dig examples (README.md:500-560 authcache, README.md:406-424 SRV).

State is written through our own registration pipeline where possible, so
these are true end-to-end contract tests: register -> ZooKeeper -> resolve
exactly as Binder would.
"""

import json

import pytest

from registrar_tpu import binderview
from registrar_tpu.records import host_record, payload_bytes
from registrar_tpu.registration import register
from registrar_tpu.testing.server import ZKServer
from registrar_tpu.zk.client import ZKClient
from registrar_tpu.zk.protocol import CreateFlag


async def _pair():
    server = await ZKServer().start()
    client = await ZKClient([server.address]).connect()
    return server, client


async def _put_host(client, path, rtype, addr, ttl=None, ports=None):
    await client.mkdirp(path.rsplit("/", 1)[0])
    await client.create(
        path, payload_bytes(host_record(rtype, addr, ttl=ttl, ports=ports)),
        CreateFlag.EPHEMERAL,
    )


class TestReadmeAuthcacheExample:
    """README.md:500-560: the authcache service with two redis_host zones."""

    async def _setup(self, client):
        reg = {
            "domain": "authcache.emy-10.joyent.us",
            "type": "redis_host",
            "ttl": 30,
            "service": {
                "type": "service",
                "service": {
                    "srvce": "_redis", "proto": "_tcp", "port": 6379, "ttl": 60,
                },
                "ttl": 60,
            },
        }
        await register(client, reg, admin_ip="172.27.10.62",
                       hostname="a2674d3b-a9c4-46bc-a835-b6ce21d522c2",
                       settle_delay=0)
        # second instance (a second registrar process in production)
        await _put_host(
            client,
            "/us/joyent/emy-10/authcache/a4ae094d-da07-4911-94f9-c982dc88f3cc",
            "redis_host", "172.27.10.67", ttl=30, ports=[6379],
        )

    async def test_service_a_query_lists_both_instances(self):
        # $ dig authcache.emy-10.joyent.us -> two A answers, TTL 30
        server, client = await _pair()
        try:
            await self._setup(client)
            res = await binderview.resolve(
                client, "authcache.emy-10.joyent.us", "A"
            )
            assert sorted(a.data for a in res.answers) == [
                "172.27.10.62", "172.27.10.67",
            ]
            assert all(a.ttl == 30 for a in res.answers)  # min(60, 30)
        finally:
            await client.close()
            await server.stop()

    async def test_direct_host_query(self):
        # $ dig a2674d3b-....authcache.emy-10.joyent.us -> 30 IN A 172.27.10.62
        server, client = await _pair()
        try:
            await self._setup(client)
            name = ("a2674d3b-a9c4-46bc-a835-b6ce21d522c2"
                    ".authcache.emy-10.joyent.us")
            res = await binderview.resolve(client, name, "A")
            (ans,) = res.answers
            assert (ans.data, ans.ttl, ans.rtype) == ("172.27.10.62", 30, "A")
        finally:
            await client.close()
            await server.stop()


class TestReadmeSrvExample:
    """README.md:406-424: _http._tcp.example.joyent.us SRV resolution."""

    async def test_srv_answers_and_additionals(self):
        server, client = await _pair()
        try:
            reg = {
                "domain": "example.joyent.us",
                "type": "load_balancer",
                "service": {
                    "type": "service",
                    "service": {"srvce": "_http", "proto": "_tcp", "port": 80},
                },
            }
            await register(client, reg, admin_ip="172.27.10.72",
                           hostname="b44c74d6", settle_delay=0)
            res = await binderview.resolve(
                client, "_http._tcp.example.joyent.us", "SRV"
            )
            (srv,) = res.answers
            # _http._tcp.example.joyent.us. 60 IN SRV 0 10 80 b44c74d6.example.joyent.us.
            assert srv.ttl == 60  # injected service default ttl
            assert srv.data == "0 10 80 b44c74d6.example.joyent.us."
            (add,) = res.additionals
            # b44c74d6.example.joyent.us. 30 IN A 172.27.10.72
            assert (add.name, add.ttl, add.data) == (
                "b44c74d6.example.joyent.us", 30, "172.27.10.72",
            )
        finally:
            await client.close()
            await server.stop()

    async def test_srv_per_port_fanout(self):
        # SRV-based discovery for multi-process zones (README.md:104-110):
        # one SRV answer per port in the host record's ports array.
        server, client = await _pair()
        try:
            reg = {
                "domain": "moray.emy-10.joyent.us",
                "type": "moray_host",
                "ports": [2021, 2022, 2023],
                "service": {
                    "type": "service",
                    "service": {"srvce": "_moray", "proto": "_tcp", "port": 2020},
                },
            }
            await register(client, reg, admin_ip="172.27.10.80",
                           hostname="m0", settle_delay=0)
            res = await binderview.resolve(
                client, "_moray._tcp.moray.emy-10.joyent.us", "SRV"
            )
            ports = sorted(int(a.data.split()[2]) for a in res.answers)
            assert ports == [2021, 2022, 2023]
            assert len(res.additionals) == 1
        finally:
            await client.close()
            await server.stop()

    async def test_srv_mismatched_service_name(self):
        server, client = await _pair()
        try:
            reg = {
                "domain": "example.joyent.us",
                "type": "load_balancer",
                "service": {
                    "type": "service",
                    "service": {"srvce": "_http", "proto": "_tcp", "port": 80},
                },
            }
            await register(client, reg, admin_ip="172.27.10.72",
                           hostname="b44c74d6", settle_delay=0)
            res = await binderview.resolve(
                client, "_https._tcp.example.joyent.us", "SRV"
            )
            assert res.empty
        finally:
            await client.close()
            await server.stop()


class TestMalformedRecords:
    async def test_malformed_service_record_resolves_as_absent(self):
        server, client = await _pair()
        try:
            await client.mkdirp("/us/test/bad")
            await client.put(
                "/us/test/bad",
                b'{"type":"service","service":{"service":"oops"}}',
            )
            res = await binderview.resolve(client, "_x._tcp.bad.test.us", "SRV")
            assert res.empty
            assert res.additionals == []
        finally:
            await client.close()
            await server.stop()

    async def test_instance_without_ports_yields_no_orphan_additional(self):
        # service record lacking a port + host record lacking ports: no SRV
        # answers, so no A additionals either (additionals only resolve
        # names that appear in SRV answers).
        server, client = await _pair()
        try:
            await client.mkdirp("/us/test/noport")
            await client.put(
                "/us/test/noport",
                b'{"type":"service","service":{"type":"service",'
                b'"service":{"srvce":"_x","proto":"_tcp"}}}',
            )
            await _put_host(client, "/us/test/noport/i0", "load_balancer",
                            "10.0.0.9")
            res = await binderview.resolve(
                client, "_x._tcp.noport.test.us", "SRV"
            )
            assert res.empty
            assert res.additionals == []
        finally:
            await client.close()
            await server.stop()


class TestTypeTable:
    """README.md:274-293: queried-directly vs usable-for-service."""

    async def test_ops_host_not_directly_queryable(self):
        server, client = await _pair()
        try:
            await _put_host(client, "/us/test/ops/box1", "ops_host", "10.0.0.1")
            res = await binderview.resolve(client, "box1.ops.test.us", "A")
            assert res.empty  # behaves as though it weren't there
        finally:
            await client.close()
            await server.stop()

    async def test_host_type_excluded_from_service(self):
        server, client = await _pair()
        try:
            reg = {
                "domain": "mixed.test.us",
                "type": "load_balancer",
                "service": {
                    "type": "service",
                    "service": {"srvce": "_http", "proto": "_tcp", "port": 80},
                },
            }
            await register(client, reg, admin_ip="10.0.0.2",
                           hostname="lb0", settle_delay=0)
            # a "host"-type record parked under the same service node
            await _put_host(client, "/us/test/mixed/plain0", "host", "10.0.0.3")
            res = await binderview.resolve(client, "mixed.test.us", "A")
            assert [a.data for a in res.answers] == ["10.0.0.2"]
            # ...but it still resolves directly
            direct = await binderview.resolve(client, "plain0.mixed.test.us", "A")
            assert [a.data for a in direct.answers] == ["10.0.0.3"]
        finally:
            await client.close()
            await server.stop()

    async def test_missing_name_empty(self):
        server, client = await _pair()
        try:
            res = await binderview.resolve(client, "no.such.name", "A")
            assert res.empty
        finally:
            await client.close()
            await server.stop()


class TestConvergence:
    async def test_two_registrars_one_service(self):
        """The production story: N independent registrar processes converge
        on one ZooKeeper ensemble (SURVEY.md §2 'distributed aspect')."""
        server = await ZKServer().start()
        c1 = await ZKClient([server.address]).connect()
        c2 = await ZKClient([server.address]).connect()
        try:
            reg = {
                "domain": "web.prod.us",
                "type": "load_balancer",
                "service": {
                    "type": "service",
                    "service": {"srvce": "_http", "proto": "_tcp", "port": 80},
                },
            }
            await register(c1, reg, admin_ip="10.1.0.1", hostname="web0",
                           settle_delay=0)
            await register(c2, reg, admin_ip="10.1.0.2", hostname="web1",
                           settle_delay=0)
            res = await binderview.resolve(c1, "web.prod.us", "A")
            assert sorted(a.data for a in res.answers) == [
                "10.1.0.1", "10.1.0.2",
            ]
            # one instance dies (session close) -> it leaves DNS
            await c2.close()
            res = await binderview.resolve(c1, "web.prod.us", "A")
            assert [a.data for a in res.answers] == ["10.1.0.1"]
        finally:
            await c1.close()
            await server.stop()


class TestTtlPrecedence:
    """The TTL precedence ladders (reference README.md:680-757): host
    records prefer the inner <type>.ttl over the top-level ttl; service
    records prefer service.service.ttl, then service.ttl, then the
    record's top-level ttl; absent everywhere falls to the default."""

    async def test_host_inner_ttl_beats_top_level(self):
        server, client = await _pair()
        try:
            rec = host_record("host", "10.0.0.1", ttl=111)
            rec["host"]["ttl"] = 222  # inner wins
            await client.mkdirp("/us/ttl/h")
            await client.create(
                "/us/ttl/h/vm", payload_bytes(rec), CreateFlag.EPHEMERAL
            )
            res = await binderview.resolve(client, "vm.h.ttl.us", "A")
            assert [a.ttl for a in res.answers] == [222]
        finally:
            await client.close()
            await server.stop()

    async def test_host_top_level_ttl_fallback(self):
        server, client = await _pair()
        try:
            await _put_host(client, "/us/ttl2/h/vm", "host", "10.0.0.2", ttl=333)
            res = await binderview.resolve(client, "vm.h.ttl2.us", "A")
            assert [a.ttl for a in res.answers] == [333]
        finally:
            await client.close()
            await server.stop()

    async def test_service_ttl_ladder(self):
        server, client = await _pair()
        try:
            path = "/us/ttl3/svc"
            await client.mkdirp(path)
            # top rung: service.service.ttl beats service.ttl AND the
            # record's top-level ttl
            svc0 = {
                "type": "service",
                "ttl": 999,
                "service": {
                    "type": "service", "ttl": 444,
                    "service": {"srvce": "_http", "proto": "_tcp",
                                "port": 80, "ttl": 111},
                },
            }
            await client.put(path, json.dumps(svc0).encode())
            await _put_host(
                client, f"{path}/i0", "load_balancer", "10.1.1.1", ports=[80]
            )
            res = await binderview.resolve(
                client, "_http._tcp.svc.ttl3.us", "SRV"
            )
            assert [a.ttl for a in res.answers] == [111]
            await client.unlink(f"{path}/i0")

            # service.ttl (middle rung): inner ttl absent
            svc = {
                "type": "service",
                "ttl": 999,
                "service": {
                    "type": "service", "ttl": 444,
                    "service": {"srvce": "_http", "proto": "_tcp", "port": 80},
                },
            }
            await client.put(path, json.dumps(svc).encode())
            await _put_host(
                client, f"{path}/i0", "load_balancer", "10.1.1.1", ports=[80]
            )
            res = await binderview.resolve(
                client, "_http._tcp.svc.ttl3.us", "SRV"
            )
            assert [a.ttl for a in res.answers] == [444]

            # top-level rung: no ttl inside service at all
            svc2 = {
                "type": "service",
                "ttl": 555,
                "service": {
                    "type": "service",
                    "service": {"srvce": "_http", "proto": "_tcp", "port": 80},
                },
            }
            await client.put(path, json.dumps(svc2).encode())
            res = await binderview.resolve(
                client, "_http._tcp.svc.ttl3.us", "SRV"
            )
            assert [a.ttl for a in res.answers] == [555]
        finally:
            await client.close()
            await server.stop()


class TestResolveEdges:
    async def test_unsupported_qtype_rejected(self):
        # pure validation: rejected before any ZooKeeper interaction,
        # so no server is needed
        with pytest.raises(ValueError):
            await binderview.resolve(None, "x.us", "AAAA")

    async def test_answer_renders_like_dig(self):
        server, client = await _pair()
        try:
            await _put_host(client, "/us/fmt/h/vm", "host", "10.9.9.9")
            res = await binderview.resolve(client, "vm.h.fmt.us", "A")
            assert str(res.answers[0]) == "vm.h.fmt.us. 30 IN A 10.9.9.9"
        finally:
            await client.close()
            await server.stop()

    async def test_instance_missing_address_is_skipped(self):
        server, client = await _pair()
        try:
            path = "/us/noaddr/svc"
            await client.mkdirp(path)
            await client.put(
                path,
                payload_bytes(
                    {"type": "service",
                     "service": {"type": "service",
                                 "service": {"srvce": "_http", "proto": "_tcp",
                                             "port": 80, "ttl": 60}}}
                ),
            )
            # a child whose inner object carries no address string
            await client.create(
                f"{path}/bad",
                json.dumps({"type": "load_balancer",
                            "load_balancer": {"ports": [80]}}).encode(),
                CreateFlag.EPHEMERAL,
            )
            await _put_host(
                client, f"{path}/ok", "load_balancer", "10.2.2.2", ports=[80]
            )
            res = await binderview.resolve(client, "svc.noaddr.us", "A")
            assert [a.data for a in res.answers] == ["10.2.2.2"]
        finally:
            await client.close()
            await server.stop()


class TestCachedParity:
    """ISSUE 4: `resolve` over a ZKCache answers identically to the
    live path, cold AND warm, across the README-derived scenarios."""

    async def _setup_tree(self, client):
        # authcache-style service with two redis_host instances
        reg = {
            "domain": "authcache.emy-10.joyent.us",
            "type": "redis_host",
            "ttl": 30,
            "service": {
                "type": "service",
                "service": {
                    "srvce": "_redis", "proto": "_tcp", "port": 6379, "ttl": 60,
                },
                "ttl": 60,
            },
        }
        await register(client, reg, admin_ip="172.27.10.62",
                       hostname="inst-a", settle_delay=0)
        await _put_host(
            client, "/us/joyent/emy-10/authcache/inst-b",
            "redis_host", "172.27.10.67", ttl=30, ports=[6379],
        )
        # SRV per-port fanout
        moray = {
            "domain": "moray.emy-10.joyent.us",
            "type": "moray_host",
            "ports": [2021, 2022],
            "service": {
                "type": "service",
                "service": {"srvce": "_moray", "proto": "_tcp", "port": 2020},
            },
        }
        await register(client, moray, admin_ip="172.27.10.80",
                       hostname="m0", settle_delay=0)
        # a non-directly-queryable type and a service-excluded type
        await _put_host(client, "/us/test/ops/box1", "ops_host", "10.0.0.1")
        await _put_host(client, "/us/joyent/emy-10/authcache/plain0",
                        "host", "10.0.0.3")

    async def test_scenarios_match_live_cold_and_warm(self):
        from registrar_tpu.zkcache import ZKCache

        server, client = await _pair()
        observer = await ZKClient([server.address]).connect()
        cache = ZKCache(observer)
        try:
            await self._setup_tree(client)
            cases = [
                ("authcache.emy-10.joyent.us", "A"),
                ("inst-a.authcache.emy-10.joyent.us", "A"),
                ("_redis._tcp.authcache.emy-10.joyent.us", "SRV"),
                ("_moray._tcp.moray.emy-10.joyent.us", "SRV"),
                ("moray.emy-10.joyent.us", "A"),
                ("box1.ops.test.us", "A"),        # resolves as absent
                ("plain0.authcache.emy-10.joyent.us", "A"),  # direct host
                ("no.such.name", "A"),            # negative
                ("_x._tcp.no.such.name", "SRV"),
            ]
            for name, qtype in cases:
                live = await binderview.resolve(client, name, qtype)
                cold = await binderview.resolve(cache, name, qtype)
                warm = await binderview.resolve(cache, name, qtype)
                for which, cached in (("cold", cold), ("warm", warm)):
                    assert sorted(map(str, cached.answers)) == sorted(
                        map(str, live.answers)
                    ), f"{name}/{qtype}: {which} cached answers diverge"
                    assert sorted(map(str, cached.additionals)) == sorted(
                        map(str, live.additionals)
                    ), f"{name}/{qtype}: {which} additionals diverge"
            assert cache.stats["hits"] > 0
        finally:
            cache.close()
            await observer.close()
            await client.close()
            await server.stop()
