"""Disk snapshot persistence for the in-process ZooKeeper server.

Real ZooKeeper survives restarts via snapshot + txlog files; the
standalone dev server models that with a JSON snapshot written on
shutdown and loaded on startup.  Pinned here: byte-faithful tree
round-trip (data, stats, ACLs, zxid), session-table survival — a client
reattaching within its timeout keeps its ephemerals across a full
server-process restart — and expiry of sessions that never come back.
"""

import asyncio
import os
import signal
import subprocess
import sys

import pytest

from registrar_tpu.testing.server import ZKServer
from registrar_tpu.zk.client import ZKClient
from registrar_tpu.zk.protocol import ACL, CreateFlag, Perms, creator_all_acl

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestSnapshotRoundTrip:
    async def test_tree_stats_acls_zxid_survive(self, tmp_path):
        snap = str(tmp_path / "zk.snap")
        server = await ZKServer().start()
        client = await ZKClient([server.address]).connect()
        await client.mkdirp("/a/b")
        await client.put("/a/b", b'{"v":1}')
        await client.put("/a/b", b'{"v":2}')  # version 1 now... (create+2 sets)
        await client.add_auth("digest", b"u:p")
        await client.create("/locked", b"x", acls=creator_all_acl("u", "p"))
        await client.set_acl(
            "/locked", creator_all_acl("u", "p") + [ACL(Perms.READ, "world", "anyone")]
        )
        stat_before = await client.stat("/a/b")
        zxid_before = server.zxid
        await client.close()
        await server.stop()
        server.save_snapshot(snap)

        restored = ZKServer()
        restored.load_snapshot(snap)
        await restored.start()
        c2 = await ZKClient([restored.address]).connect()
        try:
            assert restored.zxid == zxid_before
            data, stat = await c2.get("/a/b")
            assert data == b'{"v":2}'
            assert stat.version == stat_before.version
            assert stat.mzxid == stat_before.mzxid
            assert stat.czxid == stat_before.czxid
            acls, astat = await c2.get_acl("/locked")
            assert ACL(Perms.READ, "world", "anyone") in acls
            assert astat.aversion == 1
            # the digest guard still holds for writes
            from registrar_tpu.zk.protocol import Err, ZKError

            with pytest.raises(ZKError) as exc:
                await c2.put("/locked", b"y")
            assert exc.value.code == Err.NO_AUTH
        finally:
            await c2.close()
            await restored.stop()

    async def test_session_reattach_across_restart_keeps_ephemerals(
        self, tmp_path
    ):
        snap = str(tmp_path / "zk.snap")
        server = await ZKServer(min_session_timeout_ms=5000).start()
        port = server.port
        client = await ZKClient([server.address], timeout_ms=30000).connect()
        try:
            await client.create("/eph", b"mine", CreateFlag.EPHEMERAL)
            await server.stop()
            server.save_snapshot(snap)

            restored = ZKServer(port=port)
            restored.load_snapshot(snap)
            await restored.start()
            try:
                # The client reconnects with (session_id, passwd); the
                # restored session table must accept the reattach and the
                # ephemeral must still be there.
                deadline = asyncio.get_running_loop().time() + 15
                while True:
                    try:
                        data, stat = await client.get("/eph")
                        break
                    except Exception:
                        assert asyncio.get_running_loop().time() < deadline
                        await asyncio.sleep(0.1)
                assert data == b"mine"
                assert stat.ephemeral_owner == client.session_id
            finally:
                await restored.stop()
        finally:
            await client.close()

    async def test_dead_sessions_expire_after_load(self, tmp_path):
        snap = str(tmp_path / "zk.snap")
        server = await ZKServer(
            min_session_timeout_ms=100, max_session_timeout_ms=300
        ).start()
        client = await ZKClient([server.address], timeout_ms=100).connect()
        await client.create("/ghost", b"", CreateFlag.EPHEMERAL)
        # Drop the transport without closing the session, then persist.
        await server.drop_connections()
        client.reconnect = False
        await server.stop()
        server.save_snapshot(snap)
        await client.close()

        restored = ZKServer(
            min_session_timeout_ms=100, max_session_timeout_ms=300
        )
        restored.load_snapshot(snap)
        await restored.start()
        try:
            assert restored.get_node("/ghost") is not None  # loaded intact
            deadline = asyncio.get_running_loop().time() + 10
            while restored.get_node("/ghost") is not None:
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.05)  # sweeper expires the session
            assert restored.expired_count >= 1
        finally:
            await restored.stop()


async def _spawn_server_cli(*cli_args):
    """Start the server CLI and parse its "... listening on host:port[,...]"
    banner.  Returns (proc, addrs, banner_lines) — banner_lines holds
    everything printed before the listening line."""
    proc = subprocess.Popen(
        [sys.executable, "-m", "registrar_tpu.testing.server",
         "--port", "0", *cli_args],
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True, env={**os.environ, "PYTHONPATH": REPO},
    )
    loop = asyncio.get_running_loop()
    banner = []
    while True:
        line = await loop.run_in_executor(None, proc.stdout.readline)
        assert line, "server exited before listening"
        if "listening on" in line:
            addrs = [
                (h, int(p))
                for h, p in (
                    hp.rsplit(":", 1) for hp in line.split()[-1].split(",")
                )
            ]
            return proc, addrs, banner
        banner.append(line)


class TestSnapshotCli:
    async def test_standalone_server_persists_across_restart(self, tmp_path):
        snap = str(tmp_path / "cli.snap")

        async def start_server():
            proc, addrs, _ = await _spawn_server_cli("--snapshot-file", snap)
            return proc, addrs[0][1]

        proc, port = await start_server()
        try:
            c = await ZKClient([("127.0.0.1", port)]).connect()
            await c.mkdirp("/persisted")
            await c.put("/persisted", b"survives")
            await c.close()
        finally:
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=15)
        assert os.path.exists(snap)

        proc, port = await start_server()
        try:
            c = await ZKClient([("127.0.0.1", port)]).connect()
            assert (await c.get("/persisted"))[0] == b"survives"
            await c.close()
        finally:
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=15)

    async def test_ensemble_cli_lag_flag(self):
        # `--ensemble 2 --lag 1:60000`: member 1 serves stale reads until
        # a client sync()s through it — the CLI form of ZKEnsemble.set_lag
        # for rehearsing the read barrier by hand.
        proc, addrs, banner = await _spawn_server_cli(
            "--ensemble", "2", "--lag", "1:60000"
        )
        try:
            assert any("member 1 lagging" in line for line in banner)
            w = await ZKClient([addrs[0]]).connect()
            r = await ZKClient([addrs[1]]).connect()
            try:
                await w.create("/cli-lag", b"old")
                await r.sync("/")  # catch member 1 up to the create
                await w.put("/cli-lag", b"new")  # freezes member 1
                assert (await r.get("/cli-lag"))[0] == b"old"
                await r.sync("/")
                assert (await r.get("/cli-lag"))[0] == b"new"
            finally:
                await r.close()
                await w.close()
        finally:
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=15)

    async def test_ensemble_cli_ctl_port_controls_members(self):
        # `--ctl-port`: the line protocol the real-ensemble interop suite
        # uses (ZK_ENSEMBLE_CTL=host:port) to kill/revive members —
        # 'stop N' / 'start N' 1-based, 'ok'/'err' replies, bad input
        # answered without dropping the connection.
        proc, addrs, _ = await _spawn_server_cli(
            "--ensemble", "2", "--ctl-port", "0"
        )
        try:
            loop = asyncio.get_running_loop()
            line = await loop.run_in_executor(None, proc.stdout.readline)
            assert "ensemble control listening on" in line
            host, _, port = line.split()[-1].rpartition(":")
            reader, writer = await asyncio.open_connection(host, int(port))
            try:
                async def ctl(cmd: str) -> bytes:
                    writer.write(cmd.encode() + b"\n")
                    await writer.drain()
                    return await asyncio.wait_for(reader.readline(), 10)

                assert await ctl("stop 2") == b"ok\n"
                with pytest.raises((ConnectionError, OSError)):
                    await ZKClient([addrs[1]], reconnect=False).connect()
                assert await ctl("start 2") == b"ok\n"
                c = await ZKClient([addrs[1]]).connect()
                await c.close()
                # lag N MS: the set_lag surface over the same protocol.
                assert await ctl("lag 2 60000") == b"ok\n"
                assert await ctl("lag 2 0") == b"ok\n"
                # Errors are reported, and the connection keeps serving.
                assert (await ctl("flip 1")).startswith(b"err")
                assert (await ctl("stop 99")).startswith(b"err")
                assert (await ctl("stop")).startswith(b"err")
                assert await ctl("stop 1") == b"ok\n"
            finally:
                writer.close()
        finally:
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=15)

    async def test_ctl_port_rejected_without_ensemble(self):
        out = subprocess.run(
            [sys.executable, "-m", "registrar_tpu.testing.server",
             "--ctl-port", "0"],
            cwd=REPO, capture_output=True, text=True, timeout=30,
            env={**os.environ, "PYTHONPATH": REPO},
        )
        assert out.returncode == 2
        assert "--ctl-port requires --ensemble" in out.stderr

    async def test_lag_flag_rejected_without_ensemble(self):
        # Any member index gets the same clear message (the ensemble
        # check is hoisted above the per-spec range check).
        for spec in ("0:100", "1:100"):
            out = subprocess.run(
                [sys.executable, "-m", "registrar_tpu.testing.server",
                 "--lag", spec],
                cwd=REPO, capture_output=True, text=True, timeout=30,
                env={**os.environ, "PYTHONPATH": REPO},
            )
            assert out.returncode == 2
            assert "--lag requires --ensemble" in out.stderr
