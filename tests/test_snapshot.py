"""Disk snapshot persistence for the in-process ZooKeeper server.

Real ZooKeeper survives restarts via snapshot + txlog files; the
standalone dev server models that with a JSON snapshot written on
shutdown and loaded on startup.  Pinned here: byte-faithful tree
round-trip (data, stats, ACLs, zxid), session-table survival — a client
reattaching within its timeout keeps its ephemerals across a full
server-process restart — and expiry of sessions that never come back.
"""

import asyncio
import os
import signal
import subprocess
import sys

import pytest

from registrar_tpu.testing.server import ZKServer
from registrar_tpu.zk.client import ZKClient
from registrar_tpu.zk.protocol import ACL, CreateFlag, Perms, creator_all_acl

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestSnapshotRoundTrip:
    async def test_tree_stats_acls_zxid_survive(self, tmp_path):
        snap = str(tmp_path / "zk.snap")
        server = await ZKServer().start()
        client = await ZKClient([server.address]).connect()
        await client.mkdirp("/a/b")
        await client.put("/a/b", b'{"v":1}')
        await client.put("/a/b", b'{"v":2}')  # version 1 now... (create+2 sets)
        await client.add_auth("digest", b"u:p")
        await client.create("/locked", b"x", acls=creator_all_acl("u", "p"))
        await client.set_acl(
            "/locked", creator_all_acl("u", "p") + [ACL(Perms.READ, "world", "anyone")]
        )
        stat_before = await client.stat("/a/b")
        zxid_before = server.zxid
        await client.close()
        await server.stop()
        server.save_snapshot(snap)

        restored = ZKServer()
        restored.load_snapshot(snap)
        await restored.start()
        c2 = await ZKClient([restored.address]).connect()
        try:
            assert restored.zxid == zxid_before
            data, stat = await c2.get("/a/b")
            assert data == b'{"v":2}'
            assert stat.version == stat_before.version
            assert stat.mzxid == stat_before.mzxid
            assert stat.czxid == stat_before.czxid
            acls, astat = await c2.get_acl("/locked")
            assert ACL(Perms.READ, "world", "anyone") in acls
            assert astat.aversion == 1
            # the digest guard still holds for writes
            from registrar_tpu.zk.protocol import Err, ZKError

            with pytest.raises(ZKError) as exc:
                await c2.put("/locked", b"y")
            assert exc.value.code == Err.NO_AUTH
        finally:
            await c2.close()
            await restored.stop()

    async def test_session_reattach_across_restart_keeps_ephemerals(
        self, tmp_path
    ):
        snap = str(tmp_path / "zk.snap")
        server = await ZKServer(min_session_timeout_ms=5000).start()
        port = server.port
        client = await ZKClient([server.address], timeout_ms=30000).connect()
        try:
            await client.create("/eph", b"mine", CreateFlag.EPHEMERAL)
            await server.stop()
            server.save_snapshot(snap)

            restored = ZKServer(port=port)
            restored.load_snapshot(snap)
            await restored.start()
            try:
                # The client reconnects with (session_id, passwd); the
                # restored session table must accept the reattach and the
                # ephemeral must still be there.
                deadline = asyncio.get_running_loop().time() + 15
                while True:
                    try:
                        data, stat = await client.get("/eph")
                        break
                    except Exception:
                        assert asyncio.get_running_loop().time() < deadline
                        await asyncio.sleep(0.1)
                assert data == b"mine"
                assert stat.ephemeral_owner == client.session_id
            finally:
                await restored.stop()
        finally:
            await client.close()

    async def test_dead_sessions_expire_after_load(self, tmp_path):
        snap = str(tmp_path / "zk.snap")
        server = await ZKServer(
            min_session_timeout_ms=100, max_session_timeout_ms=300
        ).start()
        client = await ZKClient([server.address], timeout_ms=100).connect()
        await client.create("/ghost", b"", CreateFlag.EPHEMERAL)
        # Drop the transport without closing the session, then persist.
        await server.drop_connections()
        client.reconnect = False
        await server.stop()
        server.save_snapshot(snap)
        await client.close()

        restored = ZKServer(
            min_session_timeout_ms=100, max_session_timeout_ms=300
        )
        restored.load_snapshot(snap)
        await restored.start()
        try:
            assert restored.get_node("/ghost") is not None  # loaded intact
            deadline = asyncio.get_running_loop().time() + 10
            while restored.get_node("/ghost") is not None:
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.05)  # sweeper expires the session
            assert restored.expired_count >= 1
        finally:
            await restored.stop()


class TestSnapshotCli:
    async def test_standalone_server_persists_across_restart(self, tmp_path):
        snap = str(tmp_path / "cli.snap")

        async def start_server():
            proc = subprocess.Popen(
                [sys.executable, "-m", "registrar_tpu.testing.server",
                 "--port", "0", "--snapshot-file", snap],
                cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                text=True, env={**os.environ, "PYTHONPATH": REPO},
            )
            # Parse "zk test server listening on host:port" from stdout.
            loop = asyncio.get_running_loop()
            while True:
                line = await loop.run_in_executor(None, proc.stdout.readline)
                assert line, "server exited before listening"
                if "listening on" in line:
                    port = int(line.rsplit(":", 1)[1])
                    return proc, port

        proc, port = await start_server()
        try:
            c = await ZKClient([("127.0.0.1", port)]).connect()
            await c.mkdirp("/persisted")
            await c.put("/persisted", b"survives")
            await c.close()
        finally:
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=15)
        assert os.path.exists(snap)

        proc, port = await start_server()
        try:
            c = await ZKClient([("127.0.0.1", port)]).connect()
            assert (await c.get("/persisted"))[0] == b"survives"
            await c.close()
        finally:
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=15)
