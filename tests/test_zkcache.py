"""Watch-coherent resolve cache (ISSUE 4): the zkcache unit/coherence suite.

The contract under test (registrar_tpu/zkcache.py, docs/DESIGN.md):

  * a warm resolve is served entirely from memory — zero requests on
    the wire — and answers byte-identically to the live path;
  * every kind of change (data write, instance add/remove, node delete,
    node re-creation after a negative answer) invalidates the affected
    entries via the one-shot watches armed with each fill, and the next
    resolve reconverges;
  * an invalidation that races an in-flight refill can never be
    overwritten by the stale in-flight answer (generation counters);
  * a session drop / terminal expiry / failed watch re-arm degrades the
    cache to live reads; a reconnect resumes cold but authoritative;
  * concurrent misses for one path share a single in-flight fill (no
    cold-start stampede), and negative entries answer absent domains
    from memory (no absent-domain stampede);
  * the maxEntries bound evicts without breaking correctness.
"""

import asyncio

import pytest

from registrar_tpu import binderview
from registrar_tpu.records import domain_to_path, host_record, payload_bytes
from registrar_tpu.registration import register, unregister
from registrar_tpu.retry import RetryPolicy
from registrar_tpu.testing.server import ZKServer
from registrar_tpu.zk.client import ZKClient
from registrar_tpu.zk.protocol import EventType
from registrar_tpu.zkcache import ZKCache

DOMAIN = "cache.test.us"
PATH = domain_to_path(DOMAIN)  # /us/test/cache

FAST_RECONNECT = RetryPolicy(
    max_attempts=float("inf"), initial_delay=0.02, max_delay=0.25
)


def _reg():
    return {
        "domain": DOMAIN,
        "type": "load_balancer",
        "service": {
            "type": "service",
            "service": {"srvce": "_http", "proto": "_tcp", "port": 80},
        },
    }


async def _stack(n_instances=2):
    """Server + writer client (owns the registrations) + cache client."""
    server = await ZKServer().start()
    writer = await ZKClient([server.address]).connect()
    reader = await ZKClient(
        [server.address], reconnect_policy=FAST_RECONNECT
    ).connect()
    for i in range(n_instances):
        await register(
            writer, _reg(), admin_ip=f"10.7.0.{i}", hostname=f"inst{i}",
            settle_delay=0,
        )
    return server, writer, reader


def _count_posts(zk):
    """Count requests the client puts on the wire (pings excluded — the
    ping loop writes frames directly, not through _post)."""
    counter = {"n": 0}
    orig = zk._post

    def wrapper(xid, op, body, *args, **kwargs):
        counter["n"] += 1
        return orig(xid, op, body, *args, **kwargs)

    zk._post = wrapper
    return counter


async def _converge(check, timeout=5.0, interval=0.01):
    deadline = asyncio.get_running_loop().time() + timeout
    while True:
        if await check():
            return
        assert asyncio.get_running_loop().time() < deadline, (
            "cache never converged within the coherence bound"
        )
        await asyncio.sleep(interval)


class TestServedFromMemory:
    async def test_warm_resolve_is_zero_rpcs_and_identical_to_live(self):
        server, writer, reader = await _stack()
        cache = ZKCache(reader)
        try:
            live = await binderview.resolve(writer, DOMAIN, "A")
            cold = await binderview.resolve(cache, DOMAIN, "A")
            posts = _count_posts(reader)
            for _ in range(25):
                warm = await binderview.resolve(cache, DOMAIN, "A")
                warm_srv = await binderview.resolve(
                    cache, f"_http._tcp.{DOMAIN}", "SRV"
                )
            assert posts["n"] == 0, (
                f"warm resolves touched the wire ({posts['n']} requests) — "
                "the A fill already covers the SRV query's entries"
            )
            assert sorted(map(str, warm.answers)) == sorted(
                map(str, live.answers)
            )
            assert sorted(map(str, cold.answers)) == sorted(
                map(str, live.answers)
            )
            assert len(warm_srv.answers) == 2
            assert cache.hit_rate() > 0.9
        finally:
            cache.close()
            await reader.close()
            await writer.close()
            await server.stop()

    async def test_first_srv_resolve_reuses_a_fill(self):
        # A and SRV queries for one domain share the node + instance
        # entries: after an A warm-up the first SRV resolve is also
        # wire-free.
        server, writer, reader = await _stack()
        cache = ZKCache(reader)
        try:
            await binderview.resolve(cache, DOMAIN, "A")
            posts = _count_posts(reader)
            res = await binderview.resolve(cache, f"_http._tcp.{DOMAIN}", "SRV")
            assert posts["n"] == 0
            assert len(res.answers) == 2
        finally:
            cache.close()
            await reader.close()
            await writer.close()
            await server.stop()


class TestInvalidation:
    async def test_data_write_reconverges(self):
        server, writer, reader = await _stack()
        cache = ZKCache(reader)
        try:
            await binderview.resolve(cache, DOMAIN, "A")
            await writer.set_data(
                f"{PATH}/inst0",
                payload_bytes(host_record("load_balancer", "10.9.9.9")),
            )

            async def updated():
                res = await binderview.resolve(cache, DOMAIN, "A")
                return "10.9.9.9" in [a.data for a in res.answers]

            await _converge(updated)
            assert cache.stats["invalidations"] >= 1
            # the refill after an invalidation records a coherence-lag
            # observation off the node's mtime
            assert cache.stats["coherence_lag_count"] >= 1
        finally:
            cache.close()
            await reader.close()
            await writer.close()
            await server.stop()

    async def test_instance_join_and_leave_reconverge(self):
        server, writer, reader = await _stack()
        cache = ZKCache(reader)
        joiner = await ZKClient([server.address]).connect()
        try:
            await binderview.resolve(cache, DOMAIN, "A")
            nodes = await register(
                joiner, _reg(), admin_ip="10.7.0.9", hostname="late",
                settle_delay=0,
            )

            async def joined():
                res = await binderview.resolve(cache, DOMAIN, "A")
                return "10.7.0.9" in [a.data for a in res.answers]

            await _converge(joined)

            # an unregistered (deleted) record must never be served past
            # the coherence bound — the DNS-outage case the ISSUE pins
            await unregister(joiner, [n for n in nodes if n != PATH])

            async def left():
                res = await binderview.resolve(cache, DOMAIN, "A")
                return "10.7.0.9" not in [a.data for a in res.answers]

            await _converge(left)
            # and at convergence the cached answer equals the live one
            live = await binderview.resolve(writer, DOMAIN, "A")
            cached = await binderview.resolve(cache, DOMAIN, "A")
            assert sorted(a.data for a in cached.answers) == sorted(
                a.data for a in live.answers
            )
        finally:
            cache.close()
            await joiner.close()
            await reader.close()
            await writer.close()
            await server.stop()

    async def test_session_death_of_instance_leaves_no_stale_answer(self):
        # The ephemeral sweep on session close is the production "host
        # died" path: its record must leave the cached view too.
        server, writer, reader = await _stack(n_instances=1)
        dying = await ZKClient([server.address]).connect()
        cache = ZKCache(reader)
        try:
            await register(
                dying, _reg(), admin_ip="10.7.0.8", hostname="doomed",
                settle_delay=0,
            )
            res = await binderview.resolve(cache, DOMAIN, "A")
            assert "10.7.0.8" in [a.data for a in res.answers]
            await dying.close()

            async def gone():
                res = await binderview.resolve(cache, DOMAIN, "A")
                return [a.data for a in res.answers] == ["10.7.0.0"]

            await _converge(gone)
        finally:
            cache.close()
            await reader.close()
            await writer.close()
            await server.stop()


class TestNegativeCaching:
    async def test_absent_domain_served_from_memory(self):
        server, writer, reader = await _stack()
        cache = ZKCache(reader)
        try:
            res = await binderview.resolve(cache, "ghost.test.us", "A")
            assert res.empty
            posts = _count_posts(reader)
            for _ in range(20):
                res = await binderview.resolve(cache, "ghost.test.us", "A")
            assert posts["n"] == 0, "absent domain stampeded the server"
            assert res.empty
        finally:
            cache.close()
            await reader.close()
            await writer.close()
            await server.stop()

    async def test_creation_invalidates_negative_entry(self):
        server, writer, reader = await _stack(n_instances=0)
        cache = ZKCache(reader)
        try:
            res = await binderview.resolve(cache, DOMAIN, "A")
            assert res.empty  # negative-cached, exists-watch armed
            await register(
                writer, _reg(), admin_ip="10.7.1.1", hostname="born",
                settle_delay=0,
            )

            async def visible():
                res = await binderview.resolve(cache, DOMAIN, "A")
                return [a.data for a in res.answers] == ["10.7.1.1"]

            await _converge(visible)
        finally:
            cache.close()
            await reader.close()
            await writer.close()
            await server.stop()


class TestSingleFlight:
    async def test_concurrent_cold_misses_share_one_fill(self):
        server, writer, reader = await _stack()
        cache = ZKCache(reader)
        try:
            posts = _count_posts(reader)
            results = await asyncio.gather(
                *(cache.read_node(PATH) for _ in range(25))
            )
            # one fill = one read_node burst (GET_DATA + GET_CHILDREN2)
            assert posts["n"] == 2, (
                f"{posts['n']} wire requests for 25 concurrent misses"
            )
            assert all(r is not None for r in results)
            assert cache.stats["fills"] == 1
        finally:
            cache.close()
            await reader.close()
            await writer.close()
            await server.stop()


class TestGenerationCounters:
    async def test_invalidation_racing_refill_never_resurrects_stale(self):
        server, writer, reader = await _stack(n_instances=1)
        cache = ZKCache(reader)
        try:
            # Hold the refill's reply window open deterministically: the
            # loader gets its (still-current) answer, then an
            # invalidation for the path lands BEFORE the loader stores.
            release = asyncio.Event()
            orig = reader.read_node

            async def slow_read_node(path, watch=False):
                result = await orig(path, watch=watch)
                await release.wait()
                return result

            reader.read_node = slow_read_node
            fill = asyncio.create_task(cache.read_node(PATH))
            await asyncio.sleep(0.05)  # loader is parked on release
            # the racing invalidation (as the watch dispatch would do)
            cache._on_event(
                type(
                    "Ev", (), {"path": PATH,
                               "type": EventType.NODE_DATA_CHANGED},
                )()
            )
            release.set()
            result = await fill
            assert result is not None  # the read itself was valid...
            # ...but the store was discarded: nothing cached for PATH
            assert PATH not in cache._entries, (
                "stale in-flight refill was resurrected over an "
                "invalidation"
            )
        finally:
            reader.read_node = orig
            cache.close()
            await reader.close()
            await writer.close()
            await server.stop()


class TestDegradedMode:
    async def test_disconnect_degrades_then_cold_authoritative_restart(self):
        server, writer, reader = await _stack()
        cache = ZKCache(reader)
        try:
            await binderview.resolve(cache, DOMAIN, "A")
            assert cache.authoritative and cache.entries > 0
            degraded = asyncio.Event()
            entries_while_degraded = []
            def on_degraded(_reason):
                entries_while_degraded.append(cache.entries)
                degraded.set()
            cache.on("degraded", on_degraded)
            await server.drop_connections()
            # the FAST_RECONNECT policy may restore authority within
            # milliseconds; the degrade transition itself is the event
            await asyncio.wait_for(degraded.wait(), timeout=5)
            assert entries_while_degraded == [0], (
                "degraded cache kept entries"
            )
            assert cache.stats["degraded_total"] == 1

            async def restored():
                return cache.authoritative

            await _converge(restored)
            assert cache.entries == 0  # cold start
            res = await binderview.resolve(cache, DOMAIN, "A")
            assert len(res.answers) == 2
            res = await binderview.resolve(cache, DOMAIN, "A")
            assert cache.stats["hits"] > 0
        finally:
            cache.close()
            await reader.close()
            await writer.close()
            await server.stop()

    async def test_degraded_lookups_are_live_reads(self):
        server, writer, reader = await _stack()
        cache = ZKCache(reader)
        try:
            await binderview.resolve(cache, DOMAIN, "A")
            # force degraded without dropping the transport, so live
            # reads still work underneath
            reader.emit("watch_rearm_failed", RuntimeError("boom"))
            assert not cache.authoritative
            await writer.set_data(
                f"{PATH}/inst0",
                payload_bytes(host_record("load_balancer", "10.8.8.8")),
            )
            # a degraded cache must see the write IMMEDIATELY (live read,
            # no invalidation machinery involved)
            res = await binderview.resolve(cache, DOMAIN, "A")
            assert "10.8.8.8" in [a.data for a in res.answers]
            assert cache.stats["bypasses"] > 0
        finally:
            cache.close()
            await reader.close()
            await writer.close()
            await server.stop()

    async def test_terminal_expiry_degrades_permanently(self):
        server, writer, reader = await _stack()
        cache = ZKCache(reader)
        try:
            await binderview.resolve(cache, DOMAIN, "A")
            await server.expire_session(reader.session_id)
            await asyncio.sleep(0.1)

            async def degraded():
                return not cache.authoritative and reader.closed

            await _converge(degraded)
            assert cache.entries == 0
        finally:
            cache.close()
            await reader.close()
            await writer.close()
            await server.stop()


class TestRebirthCoherence:
    async def test_session_rebirth_resumes_coherent(self):
        """ISSUE 4 satellite: with surviveSessionExpiry on, force-expire
        the cache's session; the reborn session's re-armed machinery must
        leave ZERO stale answers — writes made while the cache was dark
        are visible after rebirth."""
        server, writer, _ = await _stack()
        reader = await ZKClient(
            [server.address],
            survive_session_expiry=True,
            reconnect_policy=FAST_RECONNECT,
        ).connect()
        cache = ZKCache(reader)
        try:
            await binderview.resolve(cache, DOMAIN, "A")
            reborn = asyncio.Event()
            reader.on("session_reborn", lambda _sid: reborn.set())
            await server.expire_session(reader.session_id)
            # a write the dark cache must NOT miss
            await writer.set_data(
                f"{PATH}/inst1",
                payload_bytes(host_record("load_balancer", "10.6.6.6")),
            )
            await asyncio.wait_for(reborn.wait(), timeout=10)

            async def fresh():
                if not cache.authoritative:
                    return False
                res = await binderview.resolve(cache, DOMAIN, "A")
                return "10.6.6.6" in [a.data for a in res.answers]

            await _converge(fresh)
            assert not reader.closed
            # at convergence: cached == live, zero stale
            live = await binderview.resolve(writer, DOMAIN, "A")
            cached = await binderview.resolve(cache, DOMAIN, "A")
            assert sorted(a.data for a in cached.answers) == sorted(
                a.data for a in live.answers
            )
        finally:
            cache.close()
            await reader.close()
            await writer.close()
            await server.stop()


class TestEviction:
    async def test_max_entries_bound_holds_and_evicted_paths_refill(self):
        server = await ZKServer().start()
        writer = await ZKClient([server.address]).connect()
        reader = await ZKClient([server.address]).connect()
        cache = ZKCache(reader, max_entries=2)
        try:
            for i in range(4):
                await register(
                    writer,
                    {"domain": f"d{i}.ev.us", "type": "host"},
                    admin_ip=f"10.4.0.{i}", hostname=f"h{i}", settle_delay=0,
                )
            for i in range(4):
                res = await binderview.resolve(cache, f"h{i}.d{i}.ev.us", "A")
                assert [a.data for a in res.answers] == [f"10.4.0.{i}"]
            assert cache.entries <= 2
            assert cache.stats["evictions"] >= 2
            # an evicted domain still answers correctly (transparent refill)
            res = await binderview.resolve(cache, "h0.d0.ev.us", "A")
            assert [a.data for a in res.answers] == ["10.4.0.0"]
        finally:
            cache.close()
            await reader.close()
            await writer.close()
            await server.stop()


class TestStaleWhileRevalidate:
    """ISSUE 20: serve-stale (RFC 8767 stance), opt-in ``stale_max_age_s``.

    Extends the PR-4 invariants: with the knob set, a blip serves
    bounded-age last-known-good answers instead of flushing; past the
    bound the cache refuses truthfully and flushes; a restore or a
    session death always lands on a flushed, stale-free world.  With the
    knob absent every PR-4 test above pins the flush-on-degrade default.
    """

    async def test_serves_last_known_good_through_blip(self):
        server, writer, reader = await _stack()
        cache = ZKCache(reader, stale_max_age_s=30.0)
        try:
            warm = await binderview.resolve(cache, DOMAIN, "A")
            assert cache.authoritative
            # Degrade WITHOUT killing the transport: coherence is gone
            # (watches dead) but the blip is young — serve stale.
            reader.emit("watch_rearm_failed", RuntimeError("boom"))
            assert not cache.authoritative
            res = await binderview.resolve(cache, DOMAIN, "A")
            assert sorted(a.data for a in res.answers) == sorted(
                a.data for a in warm.answers
            )
            assert cache.stats["stale_serves"] > 0
            assert cache.entries > 0  # retained, not flushed
        finally:
            cache.close()
            await reader.close()
            await writer.close()
            await server.stop()

    async def test_over_age_refuses_and_flushes(self):
        server, writer, reader = await _stack()
        cache = ZKCache(reader, stale_max_age_s=0.05)
        try:
            await binderview.resolve(cache, DOMAIN, "A")
            reader.emit("watch_rearm_failed", RuntimeError("boom"))
            assert not cache.authoritative
            await asyncio.sleep(0.1)  # cross the age bound
            # A write made after coherence died: past the bound the cache
            # must answer with live truth, never with history.
            await writer.set_data(
                f"{PATH}/inst0",
                payload_bytes(host_record("load_balancer", "10.9.9.9")),
            )
            res = await binderview.resolve(cache, DOMAIN, "A")
            assert "10.9.9.9" in [a.data for a in res.answers]
            assert cache.stats["stale_refusals"] >= 1
            assert cache.entries == 0  # the whole stale world flushed
        finally:
            cache.close()
            await reader.close()
            await writer.close()
            await server.stop()

    async def test_restore_flushes_the_stale_world(self):
        server, writer, reader = await _stack()
        cache = ZKCache(reader, stale_max_age_s=30.0)
        try:
            await binderview.resolve(cache, DOMAIN, "A")
            degraded = asyncio.Event()
            cache.on("degraded", lambda _r: degraded.set())
            await server.drop_connections()
            await asyncio.wait_for(degraded.wait(), timeout=5)

            async def restored():
                return cache.authoritative

            await _converge(restored)
            # Revalidation landed: the retained stale entries are gone
            # (cold start) — nothing cached under the dead watches can
            # leak into the authoritative world.
            assert cache.entries == 0
            res = await binderview.resolve(cache, DOMAIN, "A")
            assert len(res.answers) == 2
        finally:
            cache.close()
            await reader.close()
            await writer.close()
            await server.stop()

    async def test_rebirth_never_resurrects_stale(self):
        """Session death ALWAYS flushes, serve-stale or not: a write made
        while the cache was dark must be visible after rebirth, never the
        retained pre-death answer."""
        server, writer, _ = await _stack()
        reader = await ZKClient(
            [server.address],
            survive_session_expiry=True,
            reconnect_policy=FAST_RECONNECT,
        ).connect()
        cache = ZKCache(reader, stale_max_age_s=30.0)
        try:
            await binderview.resolve(cache, DOMAIN, "A")
            reborn = asyncio.Event()
            reader.on("session_reborn", lambda _sid: reborn.set())
            await server.expire_session(reader.session_id)
            await writer.set_data(
                f"{PATH}/inst1",
                payload_bytes(host_record("load_balancer", "10.6.6.6")),
            )
            await asyncio.wait_for(reborn.wait(), timeout=10)

            async def fresh():
                if not cache.authoritative:
                    return False
                res = await binderview.resolve(cache, DOMAIN, "A")
                return "10.6.6.6" in [a.data for a in res.answers]

            await _converge(fresh)
            live = await binderview.resolve(writer, DOMAIN, "A")
            cached = await binderview.resolve(cache, DOMAIN, "A")
            assert sorted(a.data for a in cached.answers) == sorted(
                a.data for a in live.answers
            )
        finally:
            cache.close()
            await reader.close()
            await writer.close()
            await server.stop()

    async def test_knob_validation(self):
        client = ZKClient([("127.0.0.1", 1)])
        pytest.raises(ValueError, ZKCache, client, stale_max_age_s=-1)
