"""Statefile unit tests: the handoff file's write/read/validate contract.

Every degraded shape the module promises to reject (foreign, malformed,
short passwd, stale stamp, config mismatch) is pinned here; the daemon-
level fallback-to-fresh-registration behavior rides on these verdicts
and is pinned in tests/test_restart_e2e.py.
"""

import base64
import json
import os
import stat as stat_mod
import time

import pytest

from registrar_tpu import statefile
from registrar_tpu.statefile import (
    SessionState,
    StateFileInvalid,
    StateFileMissing,
    check_resumable,
    config_fingerprint,
)


def _state(**over):
    base = dict(
        session_id=0x10023ab,
        passwd=bytes(range(16)),
        negotiated_timeout_ms=30000,
        last_zxid=0x42,
        chroot="/tenant",
        config_hash="abc123",
        znodes=["/us/test/a/box0", "/us/test/a"],
        pid=4242,
        stamp=time.time(),
    )
    base.update(over)
    return SessionState(**base)


class TestRoundTrip:
    def test_save_load_round_trip(self, tmp_path):
        path = str(tmp_path / "state.json")
        want = _state()
        statefile.save(path, want)
        got = statefile.load(path)
        assert got == want

    def test_file_is_0600(self, tmp_path):
        # The file IS the session secret: holder can delete the host's
        # DNS records.  Never group/world readable.
        path = str(tmp_path / "state.json")
        statefile.save(path, _state())
        mode = stat_mod.S_IMODE(os.stat(path).st_mode)
        assert mode == 0o600

    def test_save_replaces_atomically_no_temp_left(self, tmp_path):
        path = str(tmp_path / "state.json")
        statefile.save(path, _state(session_id=1))
        statefile.save(path, _state(session_id=2))
        assert statefile.load(path).session_id == 2
        leftovers = [n for n in os.listdir(tmp_path) if n != "state.json"]
        assert leftovers == []

    def test_clear_removes_and_is_idempotent(self, tmp_path):
        path = str(tmp_path / "state.json")
        statefile.save(path, _state())
        statefile.clear(path)
        statefile.clear(path)  # already gone: not an error
        with pytest.raises(StateFileMissing):
            statefile.load(path)

    def test_missing_file_is_its_own_error(self, tmp_path):
        with pytest.raises(StateFileMissing) as ei:
            statefile.load(str(tmp_path / "nope.json"))
        assert ei.value.reason == "missing"


class TestValidation:
    def _write(self, tmp_path, payload) -> str:
        path = str(tmp_path / "state.json")
        with open(path, "w") as f:
            f.write(payload)
        return path

    def test_non_json_is_foreign(self, tmp_path):
        path = self._write(tmp_path, "not json at all {")
        with pytest.raises(StateFileInvalid) as ei:
            statefile.load(path)
        assert ei.value.reason == "foreign"

    def test_wrong_format_marker_is_foreign(self, tmp_path):
        path = self._write(tmp_path, json.dumps({"format": "something-else"}))
        with pytest.raises(StateFileInvalid) as ei:
            statefile.load(path)
        assert ei.value.reason == "foreign"

    def test_short_passwd_rejected(self, tmp_path):
        # A truncated/tampered secret: offering it to the server would
        # just burn a refused reattach — reject at load.
        path = str(tmp_path / "state.json")
        statefile.save(path, _state())
        raw = json.load(open(path))
        raw["passwd"] = base64.b64encode(b"short").decode()
        self._write(tmp_path, json.dumps(raw))
        with pytest.raises(StateFileInvalid) as ei:
            statefile.load(path)
        assert ei.value.reason == "passwd"

    def test_non_base64_passwd_rejected(self, tmp_path):
        path = str(tmp_path / "state.json")
        statefile.save(path, _state())
        raw = json.load(open(path))
        raw["passwd"] = "!!!not-base64!!!"
        self._write(tmp_path, json.dumps(raw))
        with pytest.raises(StateFileInvalid) as ei:
            statefile.load(path)
        assert ei.value.reason == "passwd"

    def test_missing_field_is_malformed(self, tmp_path):
        path = str(tmp_path / "state.json")
        statefile.save(path, _state())
        raw = json.load(open(path))
        del raw["znodes"]
        self._write(tmp_path, json.dumps(raw))
        with pytest.raises(StateFileInvalid) as ei:
            statefile.load(path)
        assert ei.value.reason == "malformed"

    def test_bad_session_id_is_malformed(self, tmp_path):
        path = str(tmp_path / "state.json")
        statefile.save(path, _state())
        raw = json.load(open(path))
        raw["sessionId"] = "zz-not-hex"
        self._write(tmp_path, json.dumps(raw))
        with pytest.raises(StateFileInvalid) as ei:
            statefile.load(path)
        assert ei.value.reason == "malformed"


class TestResumable:
    def test_fresh_matching_state_is_resumable(self):
        assert check_resumable(_state(config_hash="h"), "h") is None

    def test_config_hash_mismatch(self):
        assert (
            check_resumable(_state(config_hash="old"), "new")
            == statefile.R_CONFIG_HASH
        )

    def test_stale_stamp_older_than_session_timeout(self):
        st = _state(config_hash="h", stamp=time.time() - 31.0,
                    negotiated_timeout_ms=30000)
        assert check_resumable(st, "h") == statefile.R_STALE_STAMP

    def test_stamp_just_inside_the_timeout_passes(self):
        st = _state(config_hash="h", stamp=time.time() - 20.0,
                    negotiated_timeout_ms=30000)
        assert check_resumable(st, "h") is None

    def test_far_future_stamp_rejected(self):
        # A broken clock / tampered stamp must not be trusted forever.
        st = _state(config_hash="h", stamp=time.time() + 3600.0,
                    negotiated_timeout_ms=30000)
        assert check_resumable(st, "h") == statefile.R_STALE_STAMP


class TestFingerprint:
    REG = {"domain": "a.b.us", "type": "host", "aliases": ["x.b.us"]}

    def test_stable_across_key_order(self):
        a = config_fingerprint(self.REG, "10.0.0.1", "/t")
        b = config_fingerprint(
            dict(reversed(list(self.REG.items()))), "10.0.0.1", "/t"
        )
        assert a == b

    def test_sensitive_to_record_shaping_inputs(self):
        base = config_fingerprint(self.REG, "10.0.0.1", "/t")
        assert config_fingerprint(self.REG, "10.0.0.2", "/t") != base
        assert config_fingerprint(self.REG, "10.0.0.1", "/u") != base
        changed = dict(self.REG, aliases=["y.b.us"])
        assert config_fingerprint(changed, "10.0.0.1", "/t") != base

    def test_none_chroot_equals_empty(self):
        assert config_fingerprint(self.REG, None, None) == config_fingerprint(
            self.REG, None, ""
        )
