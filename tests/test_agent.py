"""Orchestrator (register_plus) integration tests.

Rebuild + extension of the reference's register_plus smoke test
(reference test/register.test.js:189-214), plus the failure paths the
reference left untested (its `cfg` bug at lib/index.js:48 proves the
initial-registration-failure path never ran; SURVEY.md §4).
"""

import asyncio

import pytest

from registrar_tpu.agent import (
    DEFAULT_HEARTBEAT_INTERVAL_S,
    HEARTBEAT_FAILURE_BACKOFF_S,
    register_plus,
)
from registrar_tpu.records import parse_payload
from registrar_tpu.testing.server import ZKServer
from registrar_tpu.zk.client import ZKClient

DOMAIN = "agent.test.registrar"
PATH = "/registrar/test/agent"
REGISTRATION = {"domain": DOMAIN, "type": "load_balancer"}


async def _pair():
    server = await ZKServer().start()
    client = await ZKClient([server.address]).connect()
    return server, client


def _plus(client, **kw):
    kw.setdefault("settle_delay", 0.01)
    kw.setdefault("hostname", "agenthost")
    kw.setdefault("admin_ip", "10.7.7.7")
    return register_plus(client, kw.pop("registration", REGISTRATION), **kw)


class TestTimingDefaults:
    def test_reference_constants(self):
        # BASELINE.md: 3s heartbeat, 60s post-failure backoff
        assert DEFAULT_HEARTBEAT_INTERVAL_S == 3.0
        assert HEARTBEAT_FAILURE_BACKOFF_S == 60.0


class TestLifecycle:
    async def test_register_event_and_znodes(self):
        # reference test/register.test.js:189-214
        server, client = await _pair()
        try:
            ee = _plus(client)
            (znodes,) = await ee.wait_for("register", timeout=10)
            assert znodes == [f"{PATH}/agenthost"]
            data, st = await client.get(znodes[0])
            assert st.ephemeral_owner == client.session_id
            assert parse_payload(data)["type"] == "load_balancer"
            ee.stop()
        finally:
            await client.close()
            await server.stop()

    async def test_heartbeat_events_flow(self):
        server, client = await _pair()
        try:
            ee = _plus(client, heartbeat_interval=0.05)
            await ee.wait_for("register", timeout=10)
            (nodes1,) = await ee.wait_for("heartbeat", timeout=10)
            (nodes2,) = await ee.wait_for("heartbeat", timeout=10)
            assert nodes1 == nodes2 == ee.znodes
            ee.stop()
        finally:
            await client.close()
            await server.stop()

    async def test_initial_registration_failure_emits_error(self):
        # the path the reference's cfg bug (lib/index.js:48) would crash
        server, client = await _pair()
        try:
            ee = _plus(client, registration={"domain": DOMAIN, "type": ""})
            (err,) = await ee.wait_for("error", timeout=10)
            assert isinstance(err, ValueError)
        finally:
            await client.close()
            await server.stop()

    async def test_stop_halts_loops(self):
        server, client = await _pair()
        try:
            ee = _plus(client, heartbeat_interval=0.05)
            await ee.wait_for("register", timeout=10)
            ee.stop()
            beats = []
            ee.on("heartbeat", beats.append)
            await asyncio.sleep(0.2)
            assert beats == []
            # stop() does NOT delete znodes (left to session expiry)
            assert await client.exists(f"{PATH}/agenthost") is not None
        finally:
            await client.close()
            await server.stop()


class TestHeartbeatFailure:
    async def test_failure_backs_off_then_recovers(self, monkeypatch):
        # SURVEY.md §4 coverage gap: heartbeat-failure backoff. After a
        # failed probe the loop re-arms at max(interval, 60s) — shrunk
        # here via monkeypatch — and keeps probing without deregistering.
        import registrar_tpu.agent as agent_mod
        from registrar_tpu.retry import RetryPolicy

        monkeypatch.setattr(agent_mod, "HEARTBEAT_FAILURE_BACKOFF_S", 0.1)
        server, client = await _pair()
        try:
            ee = _plus(
                client, heartbeat_interval=0.03,
                heartbeat_retry=RetryPolicy(
                    max_attempts=1, initial_delay=0.01, max_delay=0.01
                ),
            )
            (znodes,) = await ee.wait_for("register", timeout=10)
            # destroy the node out from under the agent -> probe fails
            await client.unlink(znodes[0])
            unregisters = []
            ee.on("unregister", lambda *a: unregisters.append(a))
            (err,) = await ee.wait_for("heartbeatFailure", timeout=10)
            assert err is not None
            # re-create it; the backed-off loop recovers on its next probe
            await client.create(znodes[0], b"{}")
            await ee.wait_for("heartbeat", timeout=10)
            # heartbeat failure must NOT have deregistered (design: recovery
            # rides on session expiry or health-check ok; SURVEY.md §3.2)
            assert unregisters == []
            assert ee.znodes == znodes  # registration state untouched
            ee.stop()
        finally:
            await client.close()
            await server.stop()

    async def test_consecutive_failures_are_backoff_spaced(self, monkeypatch):
        # After a failed heartbeat the loop reschedules at
        # max(interval, HEARTBEAT_FAILURE_BACKOFF_S), not at the normal
        # cadence (reference lib/index.js:131-159) — consecutive failure
        # events must be backoff-spaced, not interval-spaced.
        import time

        import registrar_tpu.agent as agent_mod
        from registrar_tpu.retry import RetryPolicy

        monkeypatch.setattr(agent_mod, "HEARTBEAT_FAILURE_BACKOFF_S", 0.6)
        server, client = await _pair()
        try:
            ee = _plus(
                client, heartbeat_interval=0.03,
                heartbeat_retry=RetryPolicy(
                    max_attempts=1, initial_delay=0.01, max_delay=0.01
                ),
            )
            (znodes,) = await ee.wait_for("register", timeout=10)
            stamps = []
            ee.on("heartbeatFailure", lambda *a: stamps.append(time.monotonic()))
            await client.unlink(znodes[0])  # every beat now fails
            for _ in range(400):
                if len(stamps) >= 3:
                    break
                await asyncio.sleep(0.02)
            assert len(stamps) >= 3
            gaps = [b - a for a, b in zip(stamps, stamps[1:])]
            assert all(g >= 0.5 for g in gaps), gaps  # backoff, not 0.03
            ee.stop()
        finally:
            await client.close()
            await server.stop()


class TestHeartbeatRepair:
    """Opt-in repair_heartbeat_miss (SURVEY.md §3.2's flagged improvement —
    off by default; TestHeartbeatFailure above pins the default)."""

    def _fast_ee(self, client, **kw):
        from registrar_tpu.retry import RetryPolicy

        return _plus(
            client,
            heartbeat_interval=0.03,
            heartbeat_retry=RetryPolicy(
                max_attempts=1, initial_delay=0.01, max_delay=0.01
            ),
            **kw,
        )

    async def test_repair_recreates_missing_znodes(self):
        server, client = await _pair()
        try:
            ee = self._fast_ee(client, repair_heartbeat_miss=True)
            (znodes,) = await ee.wait_for("register", timeout=10)
            failures = []
            ee.on("heartbeatFailure", failures.append)

            await client.unlink(znodes[0])  # vanish without session expiry
            (renodes,) = await ee.wait_for("register", timeout=10)
            assert renodes == znodes
            assert failures  # the miss was still surfaced to operators
            data, st = await client.get(znodes[0])
            assert st.ephemeral_owner == client.session_id  # ephemeral again
            assert parse_payload(data)["type"] == "load_balancer"
            # and the loop settles back into healthy heartbeats
            await ee.wait_for("heartbeat", timeout=10)
            ee.stop()
        finally:
            await client.close()
            await server.stop()

    async def test_transient_no_node_blip_does_not_trip_repair(
        self, monkeypatch
    ):
        # ISSUE 2: one transient NO_NODE blip — a stale read from a
        # lagging follower, a probe raced with a reconnect — must NOT
        # run the repair pipeline, whose cleanup stage deletes and
        # re-creates the live znodes (a real, Binder-visible
        # deregistration window).  The agent confirms with a second,
        # immediate probe before repairing; a blip that a fresh probe
        # cannot reproduce is left alone.
        import registrar_tpu.agent as agent_mod
        from registrar_tpu.retry import RetryPolicy
        from registrar_tpu.zk.protocol import Err, ZKError

        monkeypatch.setattr(agent_mod, "HEARTBEAT_FAILURE_BACKOFF_S", 0.05)
        server, client = await _pair()

        blips = []  # armed below, AFTER the listeners are registered
        real_heartbeat = client.heartbeat

        async def blippy_heartbeat(nodes, retry=None):
            if blips:
                blips.pop()
                raise ZKError(Err.NO_NODE)
            return await real_heartbeat(nodes, retry=retry)

        client.heartbeat = blippy_heartbeat
        try:
            ee = self._fast_ee(client, repair_heartbeat_miss=True)
            (znodes,) = await ee.wait_for("register", timeout=10)
            czxid_before = (await client.stat(znodes[0])).czxid
            registers, failures = [], []
            ee.on("register", registers.append)
            ee.on("heartbeatFailure", failures.append)
            blips.append(1)  # fail exactly one upcoming probe
            # the blip fires on the next probe; then let several healthy
            # cycles pass
            await ee.wait_for("heartbeatFailure", timeout=10)
            await ee.wait_for("heartbeat", timeout=10)
            assert failures  # the blip was surfaced to operators
            assert registers == []  # ... but repair never ran
            # the znode was never deleted/re-created by a repair pipeline
            assert (await client.stat(znodes[0])).czxid == czxid_before
            ee.stop()
        finally:
            await client.close()
            await server.stop()

    async def test_repair_rolls_back_when_health_drops_mid_repair(
        self, monkeypatch
    ):
        # The race: a NO_NODE probe starts the repair pipeline (settle
        # delay + RPCs), and the health checker crosses its threshold
        # while it is in flight.  The repair must not resurrect the host —
        # it rolls its fresh znodes back out.
        import registrar_tpu.agent as agent_mod
        from registrar_tpu.retry import RetryPolicy

        monkeypatch.setattr(agent_mod, "HEARTBEAT_FAILURE_BACKOFF_S", 0.05)
        server, client = await _pair()
        try:
            ee = _plus(
                client,
                heartbeat_interval=0.03,
                heartbeat_retry=RetryPolicy(
                    max_attempts=1, initial_delay=0.01, max_delay=0.01
                ),
                repair_heartbeat_miss=True,
                settle_delay=0.3,  # wide window to land the down flip in
            )
            (znodes,) = await ee.wait_for("register", timeout=10)
            registers = []
            ee.on("register", registers.append)
            await client.unlink(znodes[0])
            await ee.wait_for("heartbeatFailure", timeout=10)
            # Repair is now inside its 0.3 s settle; health goes down.
            ee.down = True
            await asyncio.sleep(1.0)
            assert registers == []
            assert await client.exists(znodes[0]) is None
            ee.stop()
        finally:
            await client.close()
            await server.stop()

    async def test_real_threshold_crossing_mid_settle_ends_deregistered(
        self, monkeypatch, tmp_path
    ):
        # Round-4 verdict #8: the rollback race driven through the REAL
        # health checker instead of poking ee.down.  Interleaving, pinned
        # by construction: the heartbeat probe (20 ms cadence) hits
        # NO_NODE and starts the repair pipeline (500 ms settle) well
        # before the checker (80 ms cadence, threshold 2) can cross —
        # the crossing then lands ~160 ms into the settle window.  The
        # host must end deregistered; removing the rollback branch in
        # _heartbeat_loop re-registers it and fails every assertion
        # below.
        import registrar_tpu.agent as agent_mod
        from registrar_tpu.retry import RetryPolicy

        monkeypatch.setattr(agent_mod, "HEARTBEAT_FAILURE_BACKOFF_S", 0.05)
        flag = tmp_path / "healthy"
        flag.write_text("")
        server, client = await _pair()
        try:
            ee = _plus(
                client,
                heartbeat_interval=0.02,
                heartbeat_retry=RetryPolicy(
                    max_attempts=1, initial_delay=0.01, max_delay=0.01
                ),
                repair_heartbeat_miss=True,
                settle_delay=0.5,
                health_check={
                    "command": f"test -f {flag}",
                    "interval": 0.08,
                    "timeout": 1.0,
                    "threshold": 2,
                },
            )
            (znodes,) = await ee.wait_for("register", timeout=10)
            registers, fails = [], []
            ee.on("register", registers.append)
            ee.on("fail", fails.append)

            # One tick breaks both worlds: the znode vanishes (operator
            # delete) and the health command starts failing.
            flag.unlink()
            await client.unlink(znodes[0])
            # The repair is in flight (its NO_NODE probe surfaced) ...
            await ee.wait_for("heartbeatFailure", timeout=10)
            # ... and the checker crosses its threshold inside the
            # repair's settle window.
            await ee.wait_for("fail", timeout=10)
            assert ee.down
            # Let the settle finish and the rollback land.
            await asyncio.sleep(1.0)
            assert registers == [], "repair resurrected a down host"
            assert await client.exists(znodes[0]) is None
            assert ee.down
            ee.stop()
        finally:
            await client.close()
            await server.stop()

    async def test_repair_failure_emits_error_and_retries_later(
        self, monkeypatch
    ):
        # The repair pipeline itself fails (ZK hiccup mid-repair): the
        # failure surfaces as `error`, and once the fault clears a later
        # heartbeat miss repairs successfully.
        import registrar_tpu.agent as agent_mod
        import registrar_tpu.registration as register_mod

        monkeypatch.setattr(agent_mod, "HEARTBEAT_FAILURE_BACKOFF_S", 0.05)
        server, client = await _pair()
        try:
            ee = self._fast_ee(client, repair_heartbeat_miss=True)
            (znodes,) = await ee.wait_for("register", timeout=10)

            real_register = register_mod.register
            fail_once = {"armed": True}

            async def flaky_register(*a, **kw):
                if fail_once["armed"]:
                    fail_once["armed"] = False
                    raise RuntimeError("repair hiccup")
                return await real_register(*a, **kw)

            monkeypatch.setattr(register_mod, "register", flaky_register)
            err_fut = asyncio.ensure_future(ee.wait_for("error", timeout=10))
            reg_fut = asyncio.ensure_future(ee.wait_for("register", timeout=10))
            await client.unlink(znodes[0])  # trigger the miss
            (err,) = await err_fut
            assert "repair hiccup" in str(err)
            await reg_fut  # the NEXT miss repairs through the real pipeline
            assert await client.exists(znodes[0]) is not None
            ee.stop()
        finally:
            await client.close()
            await server.stop()

    async def test_repair_respects_health_down(self, monkeypatch):
        # While the health checker holds the host deregistered, a NO_NODE
        # heartbeat must NOT resurrect the znodes.
        import registrar_tpu.agent as agent_mod

        monkeypatch.setattr(agent_mod, "HEARTBEAT_FAILURE_BACKOFF_S", 0.05)
        server, client = await _pair()
        try:
            ee = self._fast_ee(client, repair_heartbeat_miss=True)
            (znodes,) = await ee.wait_for("register", timeout=10)
            ee.down = True  # what on_fail sets before unregistering
            await client.unlink(znodes[0])
            registers = []
            ee.on("register", registers.append)
            await ee.wait_for("heartbeatFailure", timeout=10)
            await ee.wait_for("heartbeatFailure", timeout=10)
            assert registers == []
            assert await client.exists(znodes[0]) is None
            # health recovery clears the latch; the next miss repairs
            ee.down = False
            await ee.wait_for("register", timeout=10)
            assert await client.exists(znodes[0]) is not None
            ee.stop()
        finally:
            await client.close()
            await server.stop()


class TestHealthIntegration:
    async def test_fail_deregisters_then_ok_reregisters(self):
        # SURVEY.md §3.3 end to end, with a command whose behavior we flip
        # via the filesystem (the reference flips /usr/bin/true|false).
        server, client = await _pair()
        try:
            import tempfile, os
            flag = tempfile.NamedTemporaryFile(delete=False)
            flag.close()
            cmd = f"test -f {flag.name}"

            ee = _plus(
                client,
                health_check={
                    "command": cmd,
                    "interval": 0.03,
                    "timeout": 1.0,
                    "threshold": 2,
                },
            )
            (znodes,) = await ee.wait_for("register", timeout=10)

            events = []
            for name in ("fail", "unregister", "ok", "register"):
                ee.on(name, lambda *a, _n=name: events.append(_n))

            unregistered = asyncio.Event()
            ee.on("unregister", lambda *a: unregistered.set())
            os.unlink(flag.name)  # start failing
            await asyncio.wait_for(unregistered.wait(), timeout=10)
            assert await client.exists(znodes[0]) is None  # really deleted

            reregistered = asyncio.Event()
            ee.on("register", lambda *a: reregistered.set())
            open(flag.name, "w").close()  # recover
            await asyncio.wait_for(reregistered.wait(), timeout=10)
            assert await client.exists(znodes[0]) is not None

            assert events[:4] == ["fail", "unregister", "ok", "register"]
            ee.stop()
            os.unlink(flag.name)
        finally:
            await client.close()
            await server.stop()

    async def test_reregister_failure_on_recovery_emits_error(self):
        # Recovery fires while ZK is unreachable: on_recover's re-register
        # must surface the failure as an `error` event, not die silently.
        server, client = await _pair()
        try:
            import os
            import tempfile

            flag = tempfile.NamedTemporaryFile(delete=False)
            flag.close()
            ee = _plus(
                client,
                heartbeat_interval=60,  # keep the heartbeat loop out of it
                health_check={
                    "command": f"test -f {flag.name}",
                    "interval": 0.03,
                    "timeout": 1.0,
                    "threshold": 2,
                },
            )
            await ee.wait_for("register", timeout=10)
            unregistered = asyncio.Event()
            ee.on("unregister", lambda *a: unregistered.set())
            os.unlink(flag.name)
            await asyncio.wait_for(unregistered.wait(), timeout=10)

            # arm the waiter BEFORE triggering: the error fires from the
            # recovery task and must not be missed in between awaits
            err_fut = asyncio.ensure_future(ee.wait_for("error", timeout=10))
            await server.stop()  # ZK gone
            open(flag.name, "w").close()  # health recovers
            (err,) = await err_fut
            assert err is not None, "re-register failure must emit 'error'"
            assert ee.down  # still down: recovery did not complete
            ee.stop()
            os.unlink(flag.name)
        finally:
            await client.close()
            await server.stop()

    async def test_unregister_failure_on_fail_emits_error(self):
        # The deregistration itself fails (ZK unreachable): `fail` is
        # emitted, then `error` — never a silent half-transition.
        server, client = await _pair()
        try:
            import os
            import tempfile

            flag = tempfile.NamedTemporaryFile(delete=False)
            flag.close()
            ee = _plus(
                client,
                heartbeat_interval=60,
                health_check={
                    "command": f"test -f {flag.name}",
                    "interval": 0.03,
                    "timeout": 1.0,
                    "threshold": 2,
                },
            )
            await ee.wait_for("register", timeout=10)
            unregisters = []
            ee.on("unregister", lambda *a: unregisters.append(a))
            err_fut = asyncio.ensure_future(ee.wait_for("error", timeout=10))
            await server.stop()  # ZK gone before the health flip
            os.unlink(flag.name)
            (err,) = await err_fut
            assert err is not None, "failed unregister must emit 'error'"
            assert not unregisters  # the success event must NOT fire
            ee.stop()
        finally:
            await client.close()
            await server.stop()

    async def test_unknown_health_record_type_emits_error(self):
        server, client = await _pair()
        try:
            ee = _plus(
                client,
                heartbeat_interval=60,
                health_check={
                    "command": "true",
                    "interval": 0.05,
                    "timeout": 1.0,
                    "threshold": 2,
                },
            )
            await ee.wait_for("register", timeout=10)
            errors = []
            ee.on("error", errors.append)
            # emit dispatches the sync listener chain inline, so the
            # error is observable immediately
            ee._health.emit("data", {"type": "weird"})
            assert errors and "weird" in str(errors[0])
            ee.stop()
        finally:
            await client.close()
            await server.stop()

    async def test_fleet_member_deregisters_cleanly_beside_siblings(self):
        # The production shape: several instances behind one domain with
        # a service record.  One instance health-failing must emit
        # `unregister` (not `error`): its owned-node list includes the
        # shared persistent service node, whose NOT_EMPTY refusal (the
        # sibling's ephemeral lives under it) reads as success.
        from registrar_tpu.registration import register

        server, client = await _pair()
        sibling = await ZKClient([server.address]).connect()
        try:
            import os
            import tempfile

            svc_registration = {
                "domain": DOMAIN,
                "type": "load_balancer",
                "service": {
                    "type": "service",
                    "service": {"srvce": "_http", "proto": "_tcp", "port": 80},
                },
            }
            await register(
                sibling, svc_registration, admin_ip="10.7.7.8",
                hostname="sibling", settle_delay=0.01,
            )

            flag = tempfile.NamedTemporaryFile(delete=False)
            flag.close()
            ee = _plus(
                client,
                registration=svc_registration,
                health_check={
                    "command": f"test -f {flag.name}",
                    "interval": 0.03,
                    "timeout": 1.0,
                    "threshold": 2,
                },
            )
            await ee.wait_for("register", timeout=10)
            errors = []
            ee.on("error", errors.append)
            unregistered = asyncio.Event()
            payload = []
            def on_unregister(_err, deleted):
                payload.append(deleted)
                unregistered.set()
            ee.on("unregister", on_unregister)
            os.unlink(flag.name)  # start failing
            await asyncio.wait_for(unregistered.wait(), timeout=10)
            assert errors == []
            # the event reports what was actually deleted: the host
            # record only — the shared service node stays and is not
            # claimed
            assert payload == [[f"{PATH}/agenthost"]]
            # my host record gone; sibling + service record intact
            assert await client.exists(f"{PATH}/agenthost") is None
            assert await client.exists(f"{PATH}/sibling") is not None
            svc = await client.exists(PATH)
            assert svc is not None and svc.ephemeral_owner == 0
            ee.stop()
        finally:
            await sibling.close()
            await client.close()
            await server.stop()

    async def test_finished_transition_tasks_are_pruned(self):
        # A daemon with a flapping health check must not accumulate
        # completed transition tasks forever.
        server, client = await _pair()
        try:
            import os
            import tempfile

            flag = tempfile.NamedTemporaryFile(delete=False)
            flag.close()
            ee = _plus(
                client,
                health_check={
                    "command": f"test -f {flag.name}",
                    "interval": 0.02,
                    "timeout": 1.0,
                    "threshold": 1,
                },
            )
            await ee.wait_for("register", timeout=10)
            for _ in range(4):  # flap: down, up, down, up ...
                unreg = asyncio.Event()
                ee.on("unregister", lambda *a: unreg.set())
                os.unlink(flag.name)
                await asyncio.wait_for(unreg.wait(), timeout=10)
                rereg = asyncio.Event()
                ee.on("register", lambda *a: rereg.set())
                open(flag.name, "w").close()
                await asyncio.wait_for(rereg.wait(), timeout=10)
            await asyncio.sleep(0.05)  # let done-callbacks run
            # only the long-lived loops remain tracked, not one task per
            # completed transition (4 flaps x 2 transitions would be 8+)
            assert len(ee._tasks) <= 2
            ee.stop()
            os.unlink(flag.name)
        finally:
            await client.close()
            await server.stop()

    async def test_flapping_does_not_double_register(self):
        server, client = await _pair()
        try:
            ee = _plus(
                client,
                health_check={
                    "command": "false",
                    "interval": 0.02,
                    "threshold": 1,
                },
            )
            await ee.wait_for("register", timeout=10)
            await ee.wait_for("unregister", timeout=10)
            # health keeps failing; no further unregister/fail spam
            fails = []
            ee.on("fail", fails.append)
            await asyncio.sleep(0.15)
            assert len(fails) == 0
            ee.stop()
        finally:
            await client.close()
            await server.stop()


class TestResumeManifest:
    """ISSUE 5: verify-not-recreate when the client reattached a
    predecessor's live session (register_plus(resume_manifest=...))."""

    SVC_REG = {
        "domain": DOMAIN,
        "type": "load_balancer",
        "service": {
            "type": "service",
            "service": {"srvce": "_http", "proto": "_tcp", "port": 80},
        },
    }

    async def test_clean_resume_adopts_without_touching_znodes(self):
        from registrar_tpu.registration import register

        server, client = await _pair()
        try:
            nodes = await register(
                client, self.SVC_REG, admin_ip="10.7.7.7",
                hostname="agenthost", settle_delay=0,
            )
            before = {n: (await client.stat(n)).czxid for n in nodes}
            outcomes = []
            ee = _plus(client, registration=self.SVC_REG,
                       resume_manifest=list(nodes))
            ee.on("resume", outcomes.append)
            (znodes,) = await ee.wait_for("register", timeout=10)
            assert sorted(znodes) == sorted(nodes)
            assert outcomes == ["reattached"]
            # zero NO_NODE: nothing was deleted or recreated
            for n in nodes:
                assert (await client.stat(n)).czxid == before[n]
            ee.stop()
        finally:
            await client.close()
            await server.stop()

    async def test_drifted_resume_falls_back_to_the_pipeline(self):
        from registrar_tpu.registration import register

        server, client = await _pair()
        try:
            nodes = await register(
                client, self.SVC_REG, admin_ip="10.7.7.7",
                hostname="agenthost", settle_delay=0,
            )
            # the host record vanished in the gap: the verify sweep must
            # catch it and the pipeline must re-register
            await client.unlink(f"{PATH}/agenthost")
            outcomes = []
            ee = _plus(client, registration=self.SVC_REG,
                       resume_manifest=list(nodes))
            ee.on("resume", outcomes.append)
            (znodes,) = await ee.wait_for("register", timeout=10)
            assert f"{PATH}/agenthost" in znodes
            assert outcomes == ["repaired"]
            st = await client.stat(f"{PATH}/agenthost")
            assert st.ephemeral_owner == client.session_id
            ee.stop()
        finally:
            await client.close()
            await server.stop()

    async def test_payload_drift_on_resume_repairs_to_contract_bytes(self):
        from registrar_tpu.registration import register

        server, client = await _pair()
        try:
            nodes = await register(
                client, REGISTRATION, admin_ip="10.7.7.7",
                hostname="agenthost", settle_delay=0,
            )
            want, _ = await client.get(nodes[0])
            await server.corrupt_node(nodes[0], b'{"evil":1}')
            ee = _plus(client, resume_manifest=list(nodes))
            await ee.wait_for("register", timeout=10)
            got, _ = await client.get(nodes[0])
            assert got == want
            ee.stop()
        finally:
            await client.close()
            await server.stop()


class TestReload:
    """ISSUE 5: SIGHUP hot-reload — ee.reload applies only the delta
    through the single-flight lock; unchanged znodes never flicker."""

    async def test_noop_reload(self):
        server, client = await _pair()
        try:
            ee = _plus(client)
            await ee.wait_for("register", timeout=10)
            st = await client.stat(f"{PATH}/agenthost")
            assert await ee.reload(dict(REGISTRATION), "10.7.7.7") == "noop"
            # byte-identical desired state: nothing touched at all
            after = await client.stat(f"{PATH}/agenthost")
            assert (after.czxid, after.mzxid) == (st.czxid, st.mzxid)
            ee.stop()
        finally:
            await client.close()
            await server.stop()

    async def test_admin_ip_change_sets_payload_in_place(self):
        server, client = await _pair()
        try:
            ee = _plus(client)
            await ee.wait_for("register", timeout=10)
            node = f"{PATH}/agenthost"
            before = await client.stat(node)
            assert await ee.reload(dict(REGISTRATION), "10.9.9.9") == "applied"
            data, after = await client.get(node)
            # same node (never deleted: czxid unchanged), new bytes
            assert after.czxid == before.czxid
            assert after.mzxid > before.mzxid
            assert parse_payload(data)["load_balancer"]["address"] == "10.9.9.9"
            ee.stop()
        finally:
            await client.close()
            await server.stop()

    async def test_alias_add_and_remove_is_a_pure_delta(self):
        server, client = await _pair()
        try:
            ee = _plus(client)
            await ee.wait_for("register", timeout=10)
            host_node = f"{PATH}/agenthost"
            host_before = await client.stat(host_node)

            with_alias = dict(REGISTRATION,
                              aliases=[f"extra.{DOMAIN}"])
            assert await ee.reload(with_alias, "10.7.7.7") == "applied"
            alias_node = f"{PATH}/extra"
            st = await client.stat(alias_node)
            assert st.ephemeral_owner == client.session_id
            assert sorted(ee.znodes) == sorted([host_node, alias_node])
            # the unchanged host record was never deleted or rewritten
            host_mid = await client.stat(host_node)
            assert (host_mid.czxid, host_mid.mzxid) == (
                host_before.czxid, host_before.mzxid
            )

            assert await ee.reload(dict(REGISTRATION), "10.7.7.7") == "applied"
            assert await client.exists(alias_node) is None
            assert ee.znodes == [host_node]
            host_after = await client.stat(host_node)
            assert (host_after.czxid, host_after.mzxid) == (
                host_before.czxid, host_before.mzxid
            )
            ee.stop()
        finally:
            await client.close()
            await server.stop()

    async def test_reload_before_registration_raises(self):
        server, client = await _pair()
        try:
            ee = _plus(client, settle_delay=5.0)  # registration in flight
            try:
                await ee.reload(dict(REGISTRATION), "10.7.7.7")
            except RuntimeError as e:
                assert "cannot reload" in str(e)
            else:
                raise AssertionError("reload before registration succeeded")
            ee.stop()
        finally:
            await client.close()
            await server.stop()

    async def test_reload_while_down_defers_to_recovery(self):
        server, client = await _pair()
        try:
            ee = _plus(client)
            await ee.wait_for("register", timeout=10)
            node = f"{PATH}/agenthost"
            # simulate a health-deregistered host: desired = absent
            ee.down = True
            await client.unlink(node)
            with_alias = dict(REGISTRATION, aliases=[f"down.{DOMAIN}"])
            assert await ee.reload(with_alias, "10.7.7.7") == "applied"
            # nothing was written while down...
            assert await client.exists(f"{PATH}/down") is None
            assert await client.exists(node) is None
            ee.stop()
        finally:
            await client.close()
            await server.stop()

    async def test_reloaded_config_drives_later_pipeline_runs(self):
        # After a reload, every recovery path must register the NEW
        # records: heartbeat repair re-runs the pipeline through the
        # shared params holder.
        server, client = await _pair()
        try:
            from registrar_tpu.retry import RetryPolicy

            ee = _plus(
                client,
                heartbeat_interval=0.05,
                heartbeat_retry=RetryPolicy(max_attempts=1),
                repair_heartbeat_miss=True,
            )
            await ee.wait_for("register", timeout=10)
            assert await ee.reload(dict(REGISTRATION), "10.8.8.8") == "applied"
            node = f"{PATH}/agenthost"
            # delete the node out-of-band: heartbeat repair must restore
            # it with the RELOADED payload, not the boot-time one
            await client.unlink(node)
            deadline = asyncio.get_running_loop().time() + 10
            while True:
                st = await client.exists(node)
                if st is not None and st.ephemeral_owner == client.session_id:
                    break
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.05)
            data, _ = await client.get(node)
            assert parse_payload(data)["load_balancer"]["address"] == "10.8.8.8"
            ee.stop()
        finally:
            await client.close()
            await server.stop()

    async def test_reload_shape_change_ephemeral_to_persistent(self):
        # REVIEW FIX: a path flipping from ephemeral host record to the
        # persistent service record (alias becomes the service domain)
        # must be unlink+recreated — a put would set_data the existing
        # ephemeral and the "service record" would silently die with
        # the session.
        server, client = await _pair()
        try:
            reg1 = dict(REGISTRATION, aliases=[f"svc.{DOMAIN}"])
            ee = _plus(client, registration=reg1)
            await ee.wait_for("register", timeout=10)
            alias_node = f"{PATH}/svc"
            st = await client.stat(alias_node)
            assert st.ephemeral_owner != 0  # host record today

            reg2 = {
                "domain": f"svc.{DOMAIN}",
                "type": "load_balancer",
                "service": {
                    "type": "service",
                    "service": {"srvce": "_http", "proto": "_tcp",
                                "port": 80},
                },
            }
            assert await ee.reload(reg2, "10.7.7.7") == "applied"
            st = await client.stat(alias_node)
            assert st.ephemeral_owner == 0, (
                "service record left ephemeral by the reload"
            )
            assert parse_payload(
                (await client.get(alias_node))[0]
            )["type"] == "service"
            host = await client.stat(f"{alias_node}/agenthost")
            assert host.ephemeral_owner == client.session_id
            ee.stop()
        finally:
            await client.close()
            await server.stop()

    async def test_reload_retry_after_midapply_failure_is_not_a_noop(self):
        # REVIEW FIX: a delta that dies mid-apply leaves params already
        # switched; a retry SIGHUP used to diff new-vs-new and answer
        # "noop" without touching ZooKeeper.  The retry must re-diff
        # from the last APPLIED records and finish the job.
        server, client = await _pair()
        try:
            ee = _plus(client)
            await ee.wait_for("register", timeout=10)
            with_alias = dict(REGISTRATION, aliases=[f"retry.{DOMAIN}"])

            real_create = client.create_ephemeral_plus
            boom = {"armed": True}

            async def failing_create(path, data=b""):
                if boom["armed"]:
                    boom["armed"] = False
                    raise ConnectionError("wire died mid-delta")
                return await real_create(path, data)

            client.create_ephemeral_plus = failing_create
            try:
                await ee.reload(with_alias, "10.7.7.7")
            except ConnectionError:
                pass
            else:
                raise AssertionError("fault never fired")
            assert await client.exists(f"{PATH}/retry") is None

            # the retry must APPLY (not "noop") and create the alias
            assert await ee.reload(with_alias, "10.7.7.7") == "applied"
            st = await client.stat(f"{PATH}/retry")
            assert st.ephemeral_owner == client.session_id
            assert sorted(ee.znodes) == sorted(
                [f"{PATH}/agenthost", f"{PATH}/retry"]
            )
            ee.stop()
        finally:
            await client.close()
            await server.stop()

    async def test_reload_revert_after_failure_cleans_partial_state(self):
        # REVIEW FIX: a forward delta A->B dies after creating one of
        # B's new nodes; the operator reverts the config to A.  The
        # revert must NOT read as "noop" (base == A) — the half-created
        # B node is in an unknown state and has to be cleaned, or it
        # serves stale DNS for as long as the session lives.
        server, client = await _pair()
        try:
            ee = _plus(client)
            await ee.wait_for("register", timeout=10)
            cfg_b = dict(REGISTRATION, aliases=[
                f"b1.{DOMAIN}", f"b2.{DOMAIN}",
            ])

            real_create = client.create_ephemeral_plus
            async def failing_create(path, data=b""):
                if path.endswith("/b2"):
                    raise ConnectionError("wire died mid-delta")
                return await real_create(path, data)

            client.create_ephemeral_plus = failing_create
            try:
                await ee.reload(cfg_b, "10.7.7.7")
            except ConnectionError:
                pass
            else:
                raise AssertionError("fault never fired")
            client.create_ephemeral_plus = real_create
            # partial state: b1 landed, b2 did not
            assert await client.exists(f"{PATH}/b1") is not None
            assert await client.exists(f"{PATH}/b2") is None

            # revert to A: must APPLY and remove the stray b1
            assert await ee.reload(dict(REGISTRATION), "10.7.7.7") == "applied"
            assert await client.exists(f"{PATH}/b1") is None
            assert ee.znodes == [f"{PATH}/agenthost"]
            st = await client.stat(f"{PATH}/agenthost")
            assert st.ephemeral_owner == client.session_id
            # and the agent is back in sync: the next identical reload
            # really is a noop
            assert await ee.reload(dict(REGISTRATION), "10.7.7.7") == "noop"
            ee.stop()
        finally:
            await client.close()
            await server.stop()


class TestHeartbeatCoalescing:
    """ISSUE 11 tentpole: services sharing one ZKClient cork their
    heartbeat sweeps into one pipelined flush, while every per-service
    contract (events, NO_NODE scoping, OwnershipError, repair) holds."""

    def _two_services(self, client, **kw):
        """Two register_plus services on ONE client, with first-register
        futures subscribed synchronously (B registers while a test still
        awaits A — a late ``wait_for`` would miss the event)."""
        loop = asyncio.get_event_loop()
        out = []
        for name in ("a", "b"):
            reg = {
                "domain": f"svc-{name}.test.registrar",
                "type": "load_balancer",
            }
            ee = _plus(client, registration=reg, hostname=f"host{name}",
                       heartbeat_interval=0.05, **kw)
            fut = loop.create_future()
            ee.once(
                "register",
                lambda z, f=fut: None if f.done() else f.set_result(z),
            )
            out.append((ee, fut))
        (ee_a, reg_a), (ee_b, reg_b) = out
        return ee_a, ee_b, reg_a, reg_b

    async def test_two_services_coalesce_into_one_flush(self):
        from registrar_tpu.agent import _coalescer_for

        server, client = await _pair()
        try:
            ee_a, ee_b, reg_a, reg_b = self._two_services(client)
            await asyncio.wait_for(reg_a, 10)
            await asyncio.wait_for(reg_b, 10)

            calls = {"many": 0, "solo": 0}
            orig_many = client.heartbeat_many
            orig_solo = client.heartbeat

            async def spy_many(groups, retry=None, on_outcome=None):
                groups = [list(g) for g in groups]
                if len(groups) > 1:
                    calls["many"] += 1
                return await orig_many(groups, retry=retry,
                                       on_outcome=on_outcome)

            async def spy_solo(nodes, retry=None):
                calls["solo"] += 1
                return await orig_solo(nodes, retry=retry)

            client.heartbeat_many = spy_many
            client.heartbeat = spy_solo
            # Both loops beat within the coalescing window: multi-group
            # sweeps must appear, and keep appearing.
            await ee_a.wait_for("heartbeat", timeout=10)
            await ee_b.wait_for("heartbeat", timeout=10)
            for _ in range(30):
                if calls["many"] >= 2:
                    break
                await asyncio.sleep(0.05)
            assert calls["many"] >= 2, (
                f"services never coalesced: {calls}"
            )
            co = _coalescer_for(client)
            assert co._attached == 2
            ee_a.stop()
            ee_b.stop()
            # detach on stop: the next single-service client is solo
            await asyncio.sleep(0.06)
            assert co._attached == 0
        finally:
            await client.close()
            await server.stop()

    async def test_sibling_failure_stays_scoped(self):
        # Deleting service A's znodes fails A's sweep with NO_NODE while
        # B keeps heartbeating — the per-group contract through the
        # coalesced flush.
        from registrar_tpu.retry import RetryPolicy

        server, client = await _pair()
        try:
            ee_a, ee_b, reg_a, reg_b = self._two_services(
                client,
                heartbeat_retry=RetryPolicy(
                    max_attempts=2, initial_delay=0.01, max_delay=0.01
                ),
            )
            znodes_a = await asyncio.wait_for(reg_a, 10)
            await asyncio.wait_for(reg_b, 10)
            failures = []
            ee_a.on("heartbeatFailure", failures.append)
            b_failures = []
            ee_b.on("heartbeatFailure", b_failures.append)
            for p in znodes_a:
                await client.unlink(p)
            (err,) = await ee_a.wait_for("heartbeatFailure", timeout=10)
            assert getattr(err, "name", None) == "NO_NODE"
            # B's loop keeps succeeding afterwards, untouched by A
            await ee_b.wait_for("heartbeat", timeout=10)
            await ee_b.wait_for("heartbeat", timeout=10)
            assert not b_failures
            ee_a.stop()
            ee_b.stop()
        finally:
            await client.close()
            await server.stop()

    async def test_solo_service_uses_plain_heartbeat(self):
        # A single register_plus on a client must keep calling
        # client.heartbeat directly (zero added latency, and tests that
        # monkeypatch it keep intercepting the probe).
        server, client = await _pair()
        try:
            seen = []
            orig = client.heartbeat

            async def spy(nodes, retry=None):
                seen.append(list(nodes))
                return await orig(nodes, retry=retry)

            client.heartbeat = spy
            ee = _plus(client, heartbeat_interval=0.05)
            await ee.wait_for("register", timeout=10)
            await ee.wait_for("heartbeat", timeout=10)
            assert seen, "solo service did not route through heartbeat()"
            ee.stop()
        finally:
            await client.close()
            await server.stop()

    async def test_coalesced_repair_contract_preserved(self):
        # repair_heartbeat_miss through the coalesced path: deleting A's
        # znodes repairs A (NO_NODE -> confirm -> pipeline) while B is
        # never deregistered or repaired.
        from registrar_tpu.retry import RetryPolicy

        server, client = await _pair()
        try:
            fast = RetryPolicy(
                max_attempts=2, initial_delay=0.01, max_delay=0.01
            )
            ee_a, ee_b, reg_a, reg_b = self._two_services(
                client, heartbeat_retry=fast, repair_heartbeat_miss=True
            )
            znodes_a = await asyncio.wait_for(reg_a, 10)
            await asyncio.wait_for(reg_b, 10)
            b_registers = []
            ee_b.on("register", b_registers.append)
            for p in znodes_a:
                await client.unlink(p)
            await ee_a.wait_for("heartbeatFailure", timeout=10)
            (reg_nodes,) = await ee_a.wait_for("register", timeout=10)
            assert reg_nodes == znodes_a  # same desired paths, recreated
            for p in reg_nodes:
                st = await client.stat(p)
                assert st.ephemeral_owner == client.session_id
            assert not b_registers  # B untouched by A's repair
            ee_a.stop()
            ee_b.stop()
        finally:
            await client.close()
            await server.stop()

    async def test_cancelled_flush_window_releases_staged_sweeps(self):
        # Review regression: a flush task cancelled mid-window must
        # cancel the staged futures — not orphan service loops parked
        # on them forever.
        from registrar_tpu.agent import HeartbeatCoalescer

        class _NeverZK:
            async def heartbeat_many(self, groups, retry=None,
                                     on_outcome=None):
                raise AssertionError("flush must not run after cancel")

        co = HeartbeatCoalescer(_NeverZK())
        co.attach()
        co.attach()  # >1 attached: sweeps stage behind the window
        sweep = asyncio.ensure_future(co.sweep(["/x"], None, 10.0))
        await asyncio.sleep(0.01)  # let it stage + start the window
        assert co._flush_task is not None
        co._flush_task.cancel()
        with pytest.raises(asyncio.CancelledError):
            await asyncio.wait_for(sweep, 1.0)
        assert co._staged == []
        co.detach()
        co.detach()

    async def test_divergent_policies_do_not_head_of_line_block(self):
        # Review regression: per-policy rounds run CONCURRENTLY — a
        # round riding a failing group's backoff must not stall another
        # policy's healthy sweep behind it.
        import time as _time

        from registrar_tpu.agent import HeartbeatCoalescer
        from registrar_tpu.retry import RetryPolicy

        slowp = RetryPolicy(max_attempts=1)
        fastp = RetryPolicy(max_attempts=2)

        class _ZK:
            async def heartbeat_many(self, groups, retry=None,
                                     on_outcome=None):
                if retry is slowp:
                    await asyncio.sleep(0.4)  # a sibling's backoff
                for i in range(len(groups)):
                    if on_outcome:
                        on_outcome(i, None)
                return [None] * len(groups)

        co = HeartbeatCoalescer(_ZK())
        co.attach()
        co.attach()
        t0 = _time.monotonic()
        slow = asyncio.ensure_future(co.sweep(["/slow"], slowp, 1.0))
        fast = asyncio.ensure_future(co.sweep(["/fast"], fastp, 1.0))
        await fast
        fast_done = _time.monotonic() - t0
        await slow
        assert fast_done < 0.3, (
            f"healthy policy's sweep took {fast_done:.2f}s — head-of-line "
            "blocked behind the slow round"
        )
        co.detach()
        co.detach()
