"""Opt-in interop tests against a real multi-member ZooKeeper ensemble.

Round-4 verdict #5: the hermetic lag model validates ``sync()`` and
failover only against this repo's own server; these tests close the loop
against a *real* 3-member Apache ZooKeeper ensemble — where a client may
genuinely land on a follower — exercising the semantics the hermetic
suite can only model: the ``sync()`` read barrier after a follower read
(``zk/client.py`` sync docstring), session-preserving failover past a
dead member, and fleet sibling-deregistration observed across members.

Configuration (the ``real-zk`` CI job provides all of it):

``ZK_HOSTS``
    Comma-separated ``host:port`` list of the ensemble members
    (e.g. ``127.0.0.1:2181,127.0.0.1:2182,127.0.0.1:2183``).
    Unset -> the whole module skips.
``ZK_ENSEMBLE_CTL``
    Optional member-control endpoint so tests can kill and revive
    members.  Either a path to an executable accepting
    ``<start|stop> <n>`` (1-based member index — CI's ``zkctl`` script
    over Apache ZooKeeper), or ``host:port`` of the hermetic ensemble's
    ``--ctl-port`` listener (``python -m registrar_tpu.testing.server
    --ensemble 3 --ctl-port ...``), which speaks the same commands as
    newline-terminated lines answered with ``ok``/``err``.
    Unset -> only the member-killing tests skip.
"""

import asyncio
import os
import subprocess
import uuid

import pytest

from registrar_tpu.records import domain_to_path
from registrar_tpu.registration import register, unregister
from registrar_tpu.zk.client import ZKClient
from registrar_tpu.zk.protocol import CreateFlag

pytestmark = pytest.mark.skipif(
    not os.environ.get("ZK_HOSTS"),
    reason="set ZK_HOSTS (host:port,host:port,...) to run real-ensemble tests",
)


def _hosts():
    out = []
    for part in os.environ["ZK_HOSTS"].split(","):
        host, _, port = part.strip().rpartition(":")
        out.append((host, int(port)))
    return out


async def _ctl(action: str, index_1based: int) -> None:
    ctl = os.environ["ZK_ENSEMBLE_CTL"]
    if ":" in ctl and "/" not in ctl:
        # host:port of a --ctl-port listener (hermetic ensemble).
        host, _, port = ctl.rpartition(":")
        reader, writer = await asyncio.open_connection(host, int(port))
        try:
            writer.write(f"{action} {index_1based}\n".encode())
            await writer.drain()
            line = await asyncio.wait_for(reader.readline(), timeout=60)
            assert line.strip() == b"ok", (action, index_1based, line)
        finally:
            writer.close()
        return
    proc = await asyncio.to_thread(
        subprocess.run,
        [ctl, action, str(index_1based)],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, (action, index_1based, proc.stderr)


def _needs_ctl():
    if not os.environ.get("ZK_ENSEMBLE_CTL"):
        pytest.skip("set ZK_ENSEMBLE_CTL to run member-killing tests")


class TestRealEnsemble:
    async def test_write_via_one_member_visible_via_all(self):
        hosts = _hosts()
        assert len(hosts) >= 3, "ensemble tests expect >= 3 members"
        writer = await ZKClient([hosts[0]]).connect()
        path = f"/ens-interop-{uuid.uuid4().hex[:8]}"
        try:
            await writer.create(path, b"fan-out")
            for member in hosts[1:]:
                reader = await ZKClient([member]).connect()
                try:
                    # sync() then read: the documented recipe for a
                    # linearizable read through any member.
                    await reader.sync(path)
                    assert (await reader.get(path))[0] == b"fan-out"
                finally:
                    await reader.close()
        finally:
            try:
                await writer.unlink(path)
            finally:
                await writer.close()

    async def test_sync_is_a_read_barrier_after_follower_reads(self):
        # At most one member leads, so with writer and reader pinned to
        # different members at least one read path below crosses a real
        # follower: sync()-then-read must always observe the newest
        # write (zk/client.py sync docstring's claim, previously
        # validated only against the in-process lag model).
        hosts = _hosts()
        writer = await ZKClient([hosts[0]]).connect()
        reader = await ZKClient([hosts[1]]).connect()
        path = f"/ens-interop-sync-{uuid.uuid4().hex[:8]}"
        try:
            await writer.create(path, b"v0")
            for i in range(25):
                payload = f"v{i + 1}".encode()
                await writer.set_data(path, payload)
                await reader.sync(path)
                assert (await reader.get(path))[0] == payload
        finally:
            try:
                await writer.unlink(path)
            finally:
                await reader.close()
                await writer.close()

    async def test_watch_armed_on_one_member_fires_from_another(self):
        hosts = _hosts()
        writer = await ZKClient([hosts[0]]).connect()
        watcher = await ZKClient([hosts[2]]).connect()
        path = f"/ens-interop-watch-{uuid.uuid4().hex[:8]}"
        try:
            await writer.create(path, b"w0")
            await watcher.sync(path)
            fired = asyncio.Event()
            watcher.watch(path, lambda ev: fired.set())
            await watcher.stat(path, watch=True)
            await writer.set_data(path, b"w1")
            await asyncio.wait_for(fired.wait(), timeout=15)
        finally:
            try:
                await writer.unlink(path)
            finally:
                await watcher.close()
                await writer.close()

    async def test_sibling_deregistration_observed_across_members(self):
        # The fleet story through different members: instance A registers
        # via member 0, instance B via member 1; A deregisters and B —
        # reading through its own member after a sync — still sees the
        # shared service record and its own ephemeral.
        hosts = _hosts()
        a = await ZKClient([hosts[0]]).connect()
        b = await ZKClient([hosts[1]]).connect()
        domain = f"ens-fleet-{uuid.uuid4().hex[:8]}.test.registrar"
        path = domain_to_path(domain)
        registration = {
            "domain": domain,
            "type": "load_balancer",
            "service": {
                "type": "service",
                "service": {"srvce": "_http", "proto": "_tcp", "port": 80},
            },
        }
        try:
            mine = await register(
                a, registration, admin_ip="10.250.2.1",
                hostname="ens-a", settle_delay=0.05,
            )
            theirs = await register(
                b, registration, admin_ip="10.250.2.2",
                hostname="ens-b", settle_delay=0.05,
            )
            deleted = await unregister(a, mine)
            assert path not in deleted  # shared service node survives
            await b.sync(path)
            children = await b.get_children(path)
            assert "ens-a" not in children
            assert "ens-b" in children
            svc = await b.stat(path)
            assert svc.ephemeral_owner == 0
            deleted = await unregister(b, theirs)
            assert path in deleted  # last one out takes the service node
        finally:
            try:
                for node in await b.get_children(path):
                    await b.unlink(f"{path}/{node}")
                await b.unlink(path)
            except Exception:  # noqa: BLE001 - already gone on success
                pass
            for p in ("/registrar/test", "/registrar"):
                try:
                    await b.unlink(p)
                except Exception:  # noqa: BLE001 - shared parents remain
                    break
            await b.close()
            await a.close()

    async def test_session_and_ephemeral_survive_member_failure(self):
        # Failover: the member carrying the session dies; the client
        # reattaches the SAME session through a surviving member and the
        # ephemeral never expires.  The daemon's ride-through story
        # (docs/OPERATIONS.md) against real ZooKeeper.
        _needs_ctl()
        hosts = _hosts()
        client = await ZKClient(hosts, timeout_ms=15000).connect()
        path = f"/ens-interop-failover-{uuid.uuid4().hex[:8]}"
        victim = None
        try:
            await client.create(path, b"still-here", CreateFlag.EPHEMERAL)
            session = client.session_id
            victim = hosts.index(client.connected_server) + 1
            await _ctl("stop", victim)
            # The client's own reconnect machinery must reattach the
            # session through a survivor (connect() shuffles the list and
            # skips the dead member).
            survivors = [h for i, h in enumerate(hosts) if i + 1 != victim]
            deadline = asyncio.get_running_loop().time() + 60
            while not (
                client.connected and client.connected_server in survivors
            ):
                assert asyncio.get_running_loop().time() < deadline, (
                    "client never reattached past the dead member"
                )
                await asyncio.sleep(0.5)
            assert client.session_id == session
            observer = await ZKClient([survivors[0]]).connect()
            try:
                await observer.sync(path)
                data, stat = await observer.get(path)
                assert data == b"still-here"
                assert stat.ephemeral_owner == session
            finally:
                await observer.close()
            await client.unlink(path)
        finally:
            if victim is not None:
                try:
                    await _ctl("start", victim)
                except Exception:  # noqa: BLE001 - leave CI teardown to kill it
                    pass
            await client.close()
