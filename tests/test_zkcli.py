"""zkcli operator tool tests: drive the real CLI against the test server."""

import asyncio
import json
import os
import subprocess
import sys

from registrar_tpu.registration import register
from registrar_tpu.testing.server import ZKServer
from registrar_tpu.zk.client import ZKClient

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_cli(server, *args):
    return subprocess.run(
        [sys.executable, "-m", "registrar_tpu.tools.zkcli",
         "-s", f"{server.host}:{server.port}", *args],
        cwd=REPO, capture_output=True, text=True, timeout=30,
        env={**os.environ, "PYTHONPATH": REPO},
    )


async def _seed(server):
    client = await ZKClient([server.address]).connect()
    reg = {
        "domain": "cli.test.us",
        "type": "load_balancer",
        "service": {
            "type": "service",
            "service": {"srvce": "_http", "proto": "_tcp", "port": 80},
        },
    }
    await register(client, reg, admin_ip="10.5.5.5", hostname="box0",
                   settle_delay=0)
    return client


class TestZkCli:
    async def test_ls_get_stat_resolve_rm(self):
        server = await ZKServer().start()
        client = await _seed(server)
        try:
            out = await asyncio.to_thread(_run_cli, server, "ls", "/us/test/cli")
            assert out.returncode == 0
            assert "box0" in out.stdout.splitlines()

            out = await asyncio.to_thread(
                _run_cli, server, "get", "/us/test/cli/box0"
            )
            assert out.returncode == 0
            rec = json.loads(out.stdout)
            assert rec["load_balancer"]["address"] == "10.5.5.5"

            out = await asyncio.to_thread(
                _run_cli, server, "stat", "/us/test/cli/box0"
            )
            assert out.returncode == 0
            assert "ephemeralOwner = 0x" in out.stdout
            assert "ephemeralOwner = 0x0" not in out.stdout  # it IS ephemeral

            out = await asyncio.to_thread(
                _run_cli, server, "resolve", "cli.test.us"
            )
            assert out.returncode == 0
            assert "10.5.5.5" in out.stdout

            out = await asyncio.to_thread(
                _run_cli, server, "resolve", "-t", "SRV",
                "_http._tcp.cli.test.us",
            )
            assert out.returncode == 0
            assert "0 10 80 box0.cli.test.us." in out.stdout
            assert "ADDITIONAL" in out.stdout

            out = await asyncio.to_thread(_run_cli, server, "tree", "/us")
            assert out.returncode == 0
            assert "box0" in out.stdout
            assert "[ephemeral" in out.stdout

            out = await asyncio.to_thread(
                _run_cli, server, "rm", "/us/test/cli/box0"
            )
            assert out.returncode == 0
            assert await client.exists("/us/test/cli/box0") is None
        finally:
            await client.close()
            await server.stop()

    async def test_write_commands(self):
        server = await ZKServer().start()
        client = await ZKClient([server.address]).connect()
        try:
            out = await asyncio.to_thread(
                _run_cli, server, "mkdirp", "/ops/deep/dir"
            )
            assert out.returncode == 0
            assert await client.exists("/ops/deep/dir") is not None

            out = await asyncio.to_thread(
                _run_cli, server, "create", "/ops/deep/dir/node", '{"a":1}'
            )
            assert out.returncode == 0
            assert out.stdout.strip() == "/ops/deep/dir/node"
            assert (await client.get("/ops/deep/dir/node"))[0] == b'{"a":1}'

            out = await asyncio.to_thread(
                _run_cli, server, "create", "-s", "/ops/deep/dir/seq-"
            )
            assert out.returncode == 0
            assert out.stdout.strip().startswith("/ops/deep/dir/seq-")

            out = await asyncio.to_thread(
                _run_cli, server, "set", "/ops/deep/dir/node", '{"a":2}'
            )
            assert out.returncode == 0
            assert "version = 1" in out.stdout
            assert (await client.get("/ops/deep/dir/node"))[0] == b'{"a":2}'

            out = await asyncio.to_thread(
                _run_cli, server, "create", "/ops/deep/dir/node", "dup"
            )
            assert out.returncode == 1
            assert "NODE_EXISTS" in out.stderr

            out = await asyncio.to_thread(_run_cli, server, "rmr", "/ops")
            assert out.returncode == 0
            assert "deleted 5 node(s)" in out.stdout  # 3 dirs + node + seq-
            assert await client.exists("/ops") is None

            out = await asyncio.to_thread(_run_cli, server, "rmr", "/")
            assert out.returncode == 1
            assert "refusing" in out.stderr

            # malformed path -> one-line error, not a traceback
            out = await asyncio.to_thread(_run_cli, server, "mkdirp", "/bad/")
            assert out.returncode == 1
            assert "zkcli:" in out.stderr
            assert "Traceback" not in out.stderr
        finally:
            await client.close()
            await server.stop()

    async def test_conditional_writes_and_create_acl(self):
        server = await ZKServer().start()
        client = await ZKClient([server.address]).connect()
        try:
            await client.create("/c", b"v0")

            # conditional set: wrong version refused, right version lands
            out = await asyncio.to_thread(
                _run_cli, server, "set", "/c", "v1", "--version", "7"
            )
            assert out.returncode == 1 and "BAD_VERSION" in out.stderr
            out = await asyncio.to_thread(
                _run_cli, server, "set", "/c", "v1", "--version", "0"
            )
            assert out.returncode == 0 and "version = 1" in out.stdout

            # conditional set never creates
            out = await asyncio.to_thread(
                _run_cli, server, "set", "/nope", "x", "--version", "0"
            )
            assert out.returncode == 1 and "NO_NODE" in out.stderr

            # conditional rm
            out = await asyncio.to_thread(
                _run_cli, server, "rm", "/c", "--version", "0"
            )
            assert out.returncode == 1 and "BAD_VERSION" in out.stderr
            out = await asyncio.to_thread(
                _run_cli, server, "rm", "/c", "--version", "1"
            )
            assert out.returncode == 0
            assert await client.exists("/c") is None

            # create with explicit ACLs
            out = await asyncio.to_thread(
                _run_cli, server, "create", "-a", "world:anyone:r",
                "/readonly", "data",
            )
            assert out.returncode == 0
            out = await asyncio.to_thread(_run_cli, server, "getacl", "/readonly")
            assert "'world,'anyone" in out.stdout and ": r\n" in out.stdout
            out = await asyncio.to_thread(
                _run_cli, server, "set", "/readonly", "x"
            )
            assert out.returncode == 1 and "NO_AUTH" in out.stderr
        finally:
            await client.close()
            await server.stop()

    async def test_sync_command(self):
        server = await ZKServer().start()
        try:
            out = await asyncio.to_thread(_run_cli, server, "sync", "/")
            assert out.returncode == 0
            assert out.stdout.strip() == "/"
        finally:
            await server.stop()

    async def test_acl_commands(self):
        from registrar_tpu.zk.protocol import digest_auth_id

        server = await ZKServer().start()
        client = await ZKClient([server.address]).connect()
        try:
            await client.create("/guarded", b"x")

            out = await asyncio.to_thread(_run_cli, server, "getacl", "/guarded")
            assert out.returncode == 0
            assert "'world,'anyone" in out.stdout
            assert ": cdrwa" in out.stdout
            assert "aversion = 0" in out.stdout

            # Lock the node down to a digest identity (keep world-read).
            ident = digest_auth_id("ops", "hunter2")
            out = await asyncio.to_thread(
                _run_cli, server, "setacl", "/guarded",
                f"digest:{ident}:cdrwa", "world:anyone:r",
            )
            assert out.returncode == 0
            assert "aversion = 1" in out.stdout

            # Unauthenticated writes are now denied...
            out = await asyncio.to_thread(
                _run_cli, server, "set", "/guarded", "y"
            )
            assert out.returncode == 1
            assert "NO_AUTH" in out.stderr

            # ...but --auth digest:user:pass opens them up.
            out = await asyncio.to_thread(
                _run_cli, server, "--auth", "digest:ops:hunter2",
                "set", "/guarded", '{"b":1}'
            )
            assert out.returncode == 0
            assert (await client.get("/guarded"))[0] == b'{"b":1}'

            out = await asyncio.to_thread(
                _run_cli, server, "getacl", "/guarded"
            )
            assert f"'digest,'{ident}" in out.stdout

            # Bad ACL spec -> usage error from argparse (exit 2).
            out = await asyncio.to_thread(
                _run_cli, server, "setacl", "/guarded", "world:anyone:xyz"
            )
            assert out.returncode == 2
        finally:
            await client.close()
            await server.stop()

    async def test_watch_streams_events(self):
        server = await ZKServer().start()
        client = await ZKClient([server.address]).connect()
        try:
            await client.mkdirp("/w")
            proc = subprocess.Popen(
                [sys.executable, "-m", "registrar_tpu.tools.zkcli",
                 "-s", f"{server.host}:{server.port}",
                 "watch", "/w", "--duration", "3"],
                cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True, env={**os.environ, "PYTHONPATH": REPO},
            )
            try:
                # the stderr banner is printed after the watches are armed
                ready = await asyncio.to_thread(proc.stderr.readline)
                assert "watching /w" in ready
                await client.create("/w/kid", b"")
                await client.put("/w", b"new")
                out, _ = await asyncio.to_thread(proc.communicate, 10)
            finally:
                if proc.poll() is None:
                    proc.kill()
            events = out.splitlines()
            assert any("childrenChanged /w" in e for e in events), events
            assert any("dataChanged /w" in e for e in events), events
        finally:
            await client.close()
            await server.stop()

    async def test_error_paths(self):
        server = await ZKServer().start()
        try:
            out = await asyncio.to_thread(_run_cli, server, "get", "/missing")
            assert out.returncode == 1
            assert "NO_NODE" in out.stderr

            out = await asyncio.to_thread(
                _run_cli, server, "resolve", "ghost.test.us"
            )
            assert out.returncode == 1
            assert "no answers" in out.stderr
        finally:
            await server.stop()

    async def test_unreachable_server(self):
        proc = subprocess.run(
            [sys.executable, "-m", "registrar_tpu.tools.zkcli",
             "-s", "127.0.0.1:1", "ls", "/"],
            cwd=REPO, capture_output=True, text=True, timeout=30,
            env={**os.environ, "PYTHONPATH": REPO},
        )
        assert proc.returncode == 1
        assert "cannot connect" in proc.stderr


class TestVerify:
    """``zkcli verify -f config.json`` (ISSUE 3 satellite): the
    reconciler's read-only diff with the 0/1/2 cron contract."""

    def _config(self, tmp_path, server):
        cfg = {
            "registration": {
                "domain": "cli.test.us",
                "type": "load_balancer",
                "service": {
                    "type": "service",
                    "service": {"srvce": "_http", "proto": "_tcp",
                                "port": 80},
                },
            },
            "adminIp": "10.5.5.5",
            "zookeeper": {
                "servers": [{"host": server.host, "port": server.port}],
            },
        }
        path = tmp_path / "config.json"
        path.write_text(json.dumps(cfg))
        return path

    def _verify(self, cfg_path):
        return subprocess.run(
            [sys.executable, "-m", "registrar_tpu.tools.zkcli",
             "verify", "-f", str(cfg_path), "--hostname", "box0",
             "--timeout", "5"],
            cwd=REPO, capture_output=True, text=True, timeout=30,
            env={**os.environ, "PYTHONPATH": REPO},
        )

    async def test_in_sync_exits_zero(self, tmp_path):
        server = await ZKServer().start()
        client = await _seed(server)
        try:
            cfg_path = self._config(tmp_path, server)
            out = await asyncio.to_thread(self._verify, cfg_path)
            assert out.returncode == 0, out.stderr
            assert "in sync" in out.stdout
        finally:
            await client.close()
            await server.stop()

    async def test_drift_exits_one_and_names_reasons(self, tmp_path):
        server = await ZKServer().start()
        client = await _seed(server)
        try:
            cfg_path = self._config(tmp_path, server)
            # two drift classes at once: corrupted host payload + a
            # clobbered service record
            await server.corrupt_node("/us/test/cli/box0", b'{"evil":1}')
            await server.corrupt_node("/us/test/cli", b'{"type":"junk"}')
            out = await asyncio.to_thread(self._verify, cfg_path)
            assert out.returncode == 1, out.stderr
            assert "drift: payload  /us/test/cli/box0" in out.stdout
            assert "drift: staleService  /us/test/cli" in out.stdout
            assert "payload=1" in out.stderr
            assert "staleService=1" in out.stderr
        finally:
            await client.close()
            await server.stop()

    async def test_wedged_server_exits_two_not_hang(self, tmp_path):
        # A server that accepts the handshake but never answers requests
        # (freeze): the audit must be deadline-bounded and exit 2, not
        # hang the cron job forever.
        server = await ZKServer().start()
        try:
            cfg_path = self._config(tmp_path, server)
            server.freeze = True
            out = await asyncio.to_thread(self._verify, cfg_path)
            assert out.returncode == 2, (out.stdout, out.stderr)
        finally:
            await server.stop()

    async def test_unreachable_exits_two(self, tmp_path):
        server = await ZKServer().start()
        cfg_path = self._config(tmp_path, server)
        await server.stop()
        out = await asyncio.to_thread(self._verify, cfg_path)
        assert out.returncode == 2
        assert "cannot connect" in out.stderr

    async def test_unreadable_config_exits_two(self, tmp_path):
        out = await asyncio.to_thread(
            self._verify, tmp_path / "missing.json"
        )
        assert out.returncode == 2


def _run_repl(server, script, *cli_args):
    """Run zkcli with no subcommand (interactive prompt) feeding ``script``
    lines on stdin — how the docs' debugging transcripts are driven."""
    return subprocess.run(
        [sys.executable, "-m", "registrar_tpu.tools.zkcli",
         "-s", f"{server.host}:{server.port}", *cli_args],
        input="".join(line + "\n" for line in script),
        cwd=REPO, capture_output=True, text=True, timeout=30,
        env={**os.environ, "PYTHONPATH": REPO},
    )


class TestZkCliRepl:
    """The interactive prompt: one session, many commands — the
    ``zkCli.sh -server`` operator workflow (reference README.md:785-807
    runs its debugging transcript inside one interactive session)."""

    async def test_session_persists_across_commands(self):
        server = await ZKServer().start()
        client = await _seed(server)
        try:
            out = await asyncio.to_thread(
                _run_repl, server,
                [
                    "# rehearsing a registrar: ephemeral + read-back",
                    "create -e /repl-host '{\"type\":\"host\"}'",
                    "stat /repl-host",
                    "get /repl-host",
                    "ls /",
                    "resolve cli.test.us",
                    "quit",
                ],
            )
            assert out.returncode == 0
            assert "/repl-host" in out.stdout
            assert '{"type":"host"}' in out.stdout
            # the one-shot "deleted (now)" warning must NOT appear: the
            # prompt's session outlives the command
            assert "deleted when this command's session" not in out.stderr
            # it really was ephemeral (non-zero owner in stat output)
            owner_lines = [
                ln for ln in out.stdout.splitlines()
                if ln.startswith("ephemeralOwner = 0x")
            ]
            assert owner_lines and owner_lines[0] != "ephemeralOwner = 0x0"
            assert "10.5.5.5" in out.stdout  # resolve worked in-session
            # session closed on quit -> the ephemeral is gone
            probe = await ZKClient([server.address]).connect()
            try:
                assert await probe.exists("/repl-host") is None
            finally:
                await probe.close()
        finally:
            await client.close()
            await server.stop()

    async def test_errors_do_not_kill_the_prompt(self):
        server = await ZKServer().start()
        try:
            out = await asyncio.to_thread(
                _run_repl, server,
                [
                    "get /missing",        # ZK error
                    "nosuchcommand /x",    # parse error
                    "get --badflag",       # usage error
                    "addauth malformed",   # bad credential shape
                    "addauth",             # missing argument
                    "",                    # blank line
                    "create /still-alive ok",
                    "get /still-alive",
                    "exit",
                ],
            )
            assert out.returncode == 0  # the prompt survived everything
            assert "NO_NODE" in out.stderr
            assert "invalid choice: 'nosuchcommand'" in out.stderr
            assert "expected scheme:credential" in out.stderr
            assert "usage: addauth" in out.stderr
            assert "ok" in out.stdout.splitlines()
        finally:
            await server.stop()

    async def test_admin_and_addauth_in_repl(self):
        server = await ZKServer().start()
        try:
            out = await asyncio.to_thread(
                _run_repl, server,
                [
                    "admin ruok",
                    "addauth digest:ops:pw",
                    "create /locked secret -a auth::cdrwa",
                    "getacl /locked",
                    "quit",
                ],
            )
            assert out.returncode == 0
            assert "imok" in out.stdout
            assert "digest" in out.stdout  # ACL minted from the live auth
        finally:
            await server.stop()

    async def test_zkcli_sh_command_aliases(self):
        # zkCli.sh operator muscle memory: delete/deleteall/getAcl/setAcl
        # work as aliases (reference README.md:787-789 tells operators to
        # use zkCli.sh; same verbs must land here).
        server = await ZKServer().start()
        try:
            out = await asyncio.to_thread(
                _run_repl, server,
                [
                    "create /alias v",
                    "getAcl /alias",
                    "setAcl /alias world:anyone:r",
                    "delete /alias",
                    "create /sub/a b",  # fails: no parent - prompt survives
                    "mkdirp /sub",
                    "create /sub/a b",
                    "deleteall /sub",
                    "ls /",
                    "quit",
                ],
            )
            assert out.returncode == 0
            assert "'world,'anyone" in out.stdout
            assert "deleted 2 node(s)" in out.stdout
            # nothing left but the system node
            assert out.stdout.splitlines()[-1] == "zookeeper"
        finally:
            await server.stop()

    async def test_eof_ends_the_prompt_cleanly(self):
        server = await ZKServer().start()
        try:
            out = await asyncio.to_thread(
                _run_repl, server, ["ls /"]  # no quit: stdin EOF ends it
            )
            assert out.returncode == 0
            assert "zookeeper" in out.stdout
        finally:
            await server.stop()

    async def test_prompt_rides_out_a_server_restart(self):
        # The one-shot CLI fails fast (reconnect off); the prompt must
        # reconnect through a ZooKeeper restart mid-investigation, like
        # zkCli.sh.
        server = await ZKServer().start()
        port = server.port
        proc = subprocess.Popen(
            [sys.executable, "-m", "registrar_tpu.tools.zkcli",
             "-s", f"127.0.0.1:{port}"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True, cwd=REPO,
            # unbuffered: the test reads stdout markers line by line
            env={**os.environ, "PYTHONPATH": REPO, "PYTHONUNBUFFERED": "1"},
        )
        try:
            proc.stdin.write("create /survives v1\n")
            proc.stdin.flush()
            # wait for the command's output, not a guessed sleep
            line = await asyncio.wait_for(
                asyncio.to_thread(proc.stdout.readline), timeout=30
            )
            assert line.strip() == "/survives"

            await server.stop()
            server = await ZKServer(port=port, snapshot=server).start()
            # cover the 0.5 s and 1.5 s reconnect retries before reading;
            # stdin lines are consumed immediately, so the margin must be
            # here, not in extra commands
            await asyncio.sleep(2.0)

            # a few attempts in case the reconnect still races: failed
            # reads fail fast with CONNECTION_LOSS, a landed one prints v1
            proc.stdin.write("get /survives\n" * 3 + "quit\n")
            proc.stdin.flush()
            # to_thread: blocking in the event loop would starve the
            # in-process ZKServer the child is talking to
            out, err = await asyncio.to_thread(proc.communicate, timeout=20)
            assert proc.returncode == 0, err
            assert "v1" in out.splitlines()  # read back through the SAME repl
        finally:
            if proc.poll() is None:
                proc.kill()
            await server.stop()

    async def test_ctrl_c_aborts_watch_not_the_session(self):
        # An open-ended `watch` at the prompt is interrupted by SIGINT
        # and the prompt (and session) keeps going.
        import signal

        server = await ZKServer().start()
        proc = subprocess.Popen(
            [sys.executable, "-m", "registrar_tpu.tools.zkcli",
             "-s", f"{server.host}:{server.port}"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True, cwd=REPO,
            # unbuffered: the test reads output markers line by line
            env={**os.environ, "PYTHONPATH": REPO, "PYTHONUNBUFFERED": "1"},
        )
        try:
            proc.stdin.write("watch /\n")  # no --duration: runs until ^C
            proc.stdin.flush()
            # SIGINT only after the watch announces itself — a fixed sleep
            # could fire before the REPL's handler is even installed
            line = await asyncio.wait_for(
                asyncio.to_thread(proc.stderr.readline), timeout=30
            )
            assert "watching /" in line
            proc.send_signal(signal.SIGINT)
            await asyncio.sleep(0.5)
            proc.stdin.write("ls /\nquit\n")
            proc.stdin.flush()
            out, err = await asyncio.to_thread(proc.communicate, timeout=20)
            assert proc.returncode == 0, err
            assert "^C" in err
            assert "zookeeper" in out  # the prompt survived the interrupt
        finally:
            if proc.poll() is None:
                proc.kill()
            await server.stop()

    async def test_ctrl_c_at_idle_prompt_keeps_the_session(self):
        # SIGINT while waiting for input must not tear the session down
        # (nor hang shutdown on the blocked stdin read): the prompt
        # consumes it and keeps serving commands.
        import signal

        server = await ZKServer().start()
        proc = subprocess.Popen(
            [sys.executable, "-m", "registrar_tpu.tools.zkcli",
             "-s", f"{server.host}:{server.port}"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True, cwd=REPO,
            # unbuffered: the test reads output markers line by line
            env={**os.environ, "PYTHONPATH": REPO, "PYTHONUNBUFFERED": "1"},
        )
        try:
            proc.stdin.write("create -e /idle-eph x\n")
            proc.stdin.flush()
            # wait for the create to echo: the REPL is provably up and
            # back at the prompt before we interrupt it
            line = await asyncio.wait_for(
                asyncio.to_thread(proc.stdout.readline), timeout=30
            )
            assert line.strip() == "/idle-eph"
            proc.send_signal(signal.SIGINT)
            await asyncio.sleep(0.3)
            assert proc.poll() is None  # still running
            # the session survived: its ephemeral is still there
            probe = await ZKClient([server.address]).connect()
            try:
                assert await probe.exists("/idle-eph") is not None
            finally:
                await probe.close()
            proc.stdin.write("stat /idle-eph\nquit\n")
            proc.stdin.flush()
            out, err = await asyncio.to_thread(proc.communicate, timeout=20)
            assert proc.returncode == 0, err
            assert "use 'quit' or ctrl-D" in err
            assert "ephemeralOwner = 0x" in out
        finally:
            if proc.poll() is None:
                proc.kill()
            await server.stop()


class TestCachedResolve:
    async def test_resolve_cached_answers_like_live(self):
        server = await ZKServer().start()
        client = await _seed(server)
        try:
            live = await asyncio.to_thread(
                _run_cli, server, "resolve", "cli.test.us"
            )
            cached = await asyncio.to_thread(
                _run_cli, server, "resolve", "--cached", "cli.test.us"
            )
            assert cached.returncode == 0
            assert cached.stdout == live.stdout
            cached_srv = await asyncio.to_thread(
                _run_cli, server, "resolve", "--cached", "-t", "SRV",
                "_http._tcp.cli.test.us",
            )
            assert cached_srv.returncode == 0
            assert "0 10 80 box0.cli.test.us." in cached_srv.stdout
            assert "ADDITIONAL" in cached_srv.stdout
        finally:
            await client.close()
            await server.stop()

    async def test_resolve_cached_absent_name_exits_one(self):
        server = await ZKServer().start()
        client = await _seed(server)
        try:
            out = await asyncio.to_thread(
                _run_cli, server, "resolve", "--cached", "ghost.test.us"
            )
            assert out.returncode == 1
            assert "no answers" in out.stderr
        finally:
            await client.close()
            await server.stop()


class TestServeView:
    async def test_serve_view_prints_answers_and_status_line(self):
        server = await ZKServer().start()
        client = await _seed(server)
        try:
            out = await asyncio.to_thread(
                _run_cli, server, "serve-view", "cli.test.us",
                "_http._tcp.cli.test.us",
                "--duration", "0.6", "--status-interval", "0.2",
            )
            assert out.returncode == 0, out.stderr
            assert ";; cli.test.us (A):" in out.stdout
            assert "10.5.5.5" in out.stdout
            # SRV qtype inferred from the _svc._proto prefix
            assert ";; _http._tcp.cli.test.us (SRV):" in out.stdout
            assert "0 10 80 box0.cli.test.us." in out.stdout
            # bunyan status line on stderr: parseable JSON with the
            # operator-facing cache fields
            status_lines = [
                json.loads(line)
                for line in out.stderr.splitlines()
                if line.startswith("{")
            ]
            assert status_lines, out.stderr
            last = status_lines[-1]
            assert last["msg"] == "cache status"
            assert last["name"] == "zkcli"
            assert last["authoritative"] is True
            assert last["hits"] >= 0 and last["misses"] > 0
            assert 0.0 <= last["hitRate"] <= 1.0
        finally:
            await client.close()
            await server.stop()

    async def test_serve_view_reprints_on_change(self):
        # A change made while serve-view runs must appear in its output
        # (the invalidation -> re-resolve -> re-print loop).
        server = await ZKServer().start()
        client = await _seed(server)
        try:
            task = asyncio.create_task(asyncio.to_thread(
                _run_cli, server, "serve-view", "cli.test.us",
                "--duration", "2.5", "--status-interval", "5",
            ))
            await asyncio.sleep(0.8)  # let it warm up
            reg = {
                "domain": "cli.test.us",
                "type": "load_balancer",
                "service": {
                    "type": "service",
                    "service": {"srvce": "_http", "proto": "_tcp", "port": 80},
                },
            }
            await register(client, reg, admin_ip="10.5.5.6",
                           hostname="box1", settle_delay=0)
            out = await task
            assert out.returncode == 0, out.stderr
            assert "10.5.5.6" in out.stdout, (
                "serve-view never re-printed the updated answer set"
            )
        finally:
            await client.close()
            await server.stop()

    async def test_serve_view_honors_config_file(self, tmp_path):
        server = await ZKServer().start()
        client = await _seed(server)
        try:
            cfg = tmp_path / "cfg.json"
            cfg.write_text(json.dumps({
                "registration": {"domain": "cli.test.us", "type": "host"},
                "zookeeper": {
                    "servers": [{"host": server.host, "port": server.port}],
                },
                "cache": {"maxEntries": 16},
            }))
            out = await asyncio.to_thread(
                subprocess.run,
                [sys.executable, "-m", "registrar_tpu.tools.zkcli",
                 "-s", "127.0.0.1:1",  # dead: must use the config's servers
                 "serve-view", "cli.test.us", "-f", str(cfg),
                 "--duration", "0.4", "--status-interval", "0.2"],
                **{"cwd": REPO, "capture_output": True, "text": True,
                   "timeout": 30, "env": {**os.environ, "PYTHONPATH": REPO}},
            )
            assert out.returncode == 0, out.stderr
            assert "10.5.5.5" in out.stdout
        finally:
            await client.close()
            await server.stop()


def _run_tool(*args):
    """Run zkcli without the -s flag (raw local commands like `state`)."""
    return subprocess.run(
        [sys.executable, "-m", "registrar_tpu.tools.zkcli", *args],
        cwd=REPO, capture_output=True, text=True, timeout=30,
        env={**os.environ, "PYTHONPATH": REPO},
    )


class TestStateCommand:
    """`zkcli state FILE`: local handoff-statefile inspection (ISSUE 5)."""

    def _save(self, tmp_path, **over):
        import time

        from registrar_tpu import statefile

        base = dict(
            session_id=0xABC123,
            passwd=b"\x01" * 16,
            negotiated_timeout_ms=30000,
            last_zxid=7,
            chroot="",
            config_hash="deadbeef",
            znodes=["/us/test/cli/box0"],
            pid=111,
            stamp=time.time(),
        )
        base.update(over)
        path = tmp_path / "state.json"
        statefile.save(str(path), statefile.SessionState(**base))
        return path

    async def test_fresh_state_is_resumable_exit_zero(self, tmp_path):
        path = self._save(tmp_path)
        out = await asyncio.to_thread(_run_tool, "state", str(path))
        assert out.returncode == 0, out.stderr
        assert "sessionId = 0xabc123" in out.stdout
        assert "resumable = yes" in out.stdout
        assert "/us/test/cli/box0" in out.stdout

    async def test_stale_state_exits_one_with_reason(self, tmp_path):
        import time

        path = self._save(tmp_path, stamp=time.time() - 120)
        out = await asyncio.to_thread(_run_tool, "state", str(path))
        assert out.returncode == 1
        assert "resumable = no (staleStamp)" in out.stdout

    async def test_corrupt_state_exits_two(self, tmp_path):
        path = tmp_path / "state.json"
        path.write_text("{ not json")
        out = await asyncio.to_thread(_run_tool, "state", str(path))
        assert out.returncode == 2
        assert "reason: foreign" in out.stderr

    async def test_config_fingerprint_mismatch_exits_one(self, tmp_path):
        path = self._save(tmp_path)  # hash "deadbeef": matches nothing
        cfg = tmp_path / "config.json"
        cfg.write_text(json.dumps({
            "registration": {"domain": "cli.test.us", "type": "host"},
            "zookeeper": {"servers": [{"host": "h", "port": 1}]},
        }))
        out = await asyncio.to_thread(
            _run_tool, "state", str(path), "--config", str(cfg)
        )
        assert out.returncode == 1
        assert "resumable = no (configHash)" in out.stdout


class TestDrainCommand:
    """`zkcli drain -f config`: external deregistration (ISSUE 5)."""

    def _config(self, tmp_path, server):
        cfg = tmp_path / "config.json"
        cfg.write_text(json.dumps({
            "registration": {
                "domain": "cli.test.us",
                "type": "load_balancer",
                "service": {
                    "type": "service",
                    "service": {"srvce": "_http", "proto": "_tcp",
                                "port": 80},
                },
            },
            "adminIp": "10.5.5.5",
            "zookeeper": {
                "servers": [{"host": server.host, "port": server.port}],
            },
        }))
        return cfg

    async def test_drain_deletes_this_hosts_records(self, tmp_path):
        server = await ZKServer().start()
        client = await _seed(server)
        try:
            cfg = self._config(tmp_path, server)
            out = await asyncio.to_thread(
                _run_tool, "drain", "-f", str(cfg), "--hostname", "box0"
            )
            assert out.returncode == 0, out.stderr
            assert "deleted /us/test/cli/box0" in out.stdout
            assert "deleted /us/test/cli" in out.stdout  # childless now
            assert await client.exists("/us/test/cli/box0") is None
        finally:
            await client.close()
            await server.stop()

    async def test_drain_keeps_shared_service_node(self, tmp_path):
        from registrar_tpu.zk.protocol import CreateFlag

        server = await ZKServer().start()
        client = await _seed(server)
        try:
            # a live sibling keeps the shared domain node occupied
            await client.create(
                "/us/test/cli/sibling", b"{}", CreateFlag.EPHEMERAL
            )
            cfg = self._config(tmp_path, server)
            out = await asyncio.to_thread(
                _run_tool, "drain", "-f", str(cfg), "--hostname", "box0"
            )
            assert out.returncode == 0, out.stderr
            assert "deleted /us/test/cli/box0" in out.stdout
            assert "skipped /us/test/cli (shared (kept))" in out.stdout
            assert await client.exists("/us/test/cli/sibling") is not None
            assert await client.exists("/us/test/cli") is not None
        finally:
            await client.close()
            await server.stop()

    async def test_drain_of_absent_host_is_clean(self, tmp_path):
        server = await ZKServer().start()
        try:
            cfg = self._config(tmp_path, server)
            out = await asyncio.to_thread(
                _run_tool, "drain", "-f", str(cfg), "--hostname", "ghost"
            )
            assert out.returncode == 0
            assert "already absent" in out.stdout
        finally:
            await server.stop()

    async def test_drain_unreachable_exits_two(self, tmp_path):
        cfg = tmp_path / "config.json"
        cfg.write_text(json.dumps({
            "registration": {"domain": "cli.test.us", "type": "host"},
            "zookeeper": {"servers": [{"host": "127.0.0.1", "port": 1}]},
        }))
        out = await asyncio.to_thread(
            _run_tool, "drain", "-f", str(cfg), "--timeout", "2"
        )
        assert out.returncode == 2
        assert "cannot connect" in out.stderr


class TestServeSharded:
    """ISSUE 12: zkcli serve-sharded runs the sharded tier standalone
    per the config's serve block, SIGHUP reshards it in place, and the
    metrics listener serves the per-shard /status rollup."""

    async def test_serve_sharded_e2e_with_sighup_reshard(self, tmp_path):
        import signal as signal_mod
        import socket
        import urllib.request

        server = await ZKServer().start()
        client = await _seed(server)
        proc = None
        try:
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                port = s.getsockname()[1]
            cfg = tmp_path / "cfg.json"

            def write_cfg(shards):
                cfg.write_text(json.dumps({
                    "registration": {"domain": "cli.test.us",
                                     "type": "host"},
                    "zookeeper": {
                        "servers": [
                            {"host": server.host, "port": server.port}
                        ],
                    },
                    "serve": {
                        "shards": shards,
                        "socketPath": str(tmp_path / "resolve.sock"),
                        "attachSpread": "any",
                    },
                    "metrics": {"port": port},
                    # ISSUE 13: cross-process tracing across the whole
                    # tier — router relay spans + per-worker recorders.
                    "observability": {"sampleRate": 1.0},
                }))

            write_cfg(2)
            proc = subprocess.Popen(
                [sys.executable, "-m", "registrar_tpu.tools.zkcli",
                 "serve-sharded", "-f", str(cfg), "--duration", "30"],
                cwd=REPO, text=True,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                env={**os.environ, "PYTHONPATH": REPO},
            )

            def fetch_status():
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/status", timeout=5
                ) as resp:
                    return json.loads(resp.read())

            async def poll_status(pred, what, timeout=25.0):
                loop = asyncio.get_running_loop()
                deadline = loop.time() + timeout
                while True:
                    assert proc.poll() is None, proc.stderr.read()
                    try:
                        snapshot = await asyncio.to_thread(fetch_status)
                        if pred(snapshot):
                            return snapshot
                    except OSError:
                        pass
                    assert loop.time() < deadline, f"timed out: {what}"
                    await asyncio.sleep(0.1)

            snapshot = await poll_status(
                lambda s: s.get("serve", {}).get("shards") == 2
                and not s.get("degraded"),
                "tier up with 2 shards",
            )
            assert set(snapshot["shards"]) == {"0", "1"}
            assert snapshot["uptime_s"] is not None
            assert "serve" in snapshot["last_transition"]

            # The tier answers through its front socket — and the ONE
            # traced resolve is the ISSUE-13 acceptance resolve below.
            from registrar_tpu import trace as trace_mod
            from registrar_tpu.shard import ShardClient

            tracer = trace_mod.Tracer(sample_rate=1.0)
            sc = await ShardClient(
                str(tmp_path / "resolve.sock")
            ).connect()
            try:
                with tracer.span("client.request") as root:
                    res = await sc.resolve("cli.test.us", "A")
                assert [a.data for a in res.answers] == ["10.5.5.5"]
            finally:
                await sc.close()

            # ISSUE 13 acceptance: GET /debug/trace?id= off the metrics
            # listener assembles ONE merged tree — router relay span,
            # the owning worker's resolve/cache subtree, and its zk.op
            # spans — all sharing the client's trace id.
            def fetch_tree():
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/debug/trace"
                    f"?id={root.trace_id}", timeout=5
                ) as resp:
                    return json.loads(resp.read())

            tree = await asyncio.to_thread(fetch_tree)
            assert tree["trace_id"] == root.trace_id
            names_by_proc = set()

            def walk(node):
                names_by_proc.add((node["name"], node.get("proc")))
                for child in node.get("children", ()):
                    walk(child)

            for tree_root in tree["roots"]:
                walk(tree_root)
            names = {n for n, _p in names_by_proc}
            assert "shard.relay" in names
            assert "resolve.query" in names
            assert "cache.fill" in names and "zk.op" in names
            worker_procs = {
                p for n, p in names_by_proc if n == "resolve.query"
            }
            assert worker_procs and all(
                p and p.startswith("shard") for p in worker_procs
            )
            # the client's root span was not collected (it lives in
            # THIS process) — the relay surfaces under <missing
            # parent> instead of vanishing, per the orphan convention
            from registrar_tpu import traceview

            assert tree["orphans"] >= 1
            assert tree["roots"][-1]["name"] == traceview.MISSING_PARENT

            # ...and `zkcli trace --id` renders the same tree.
            out = _run_tool("trace", "-f", str(cfg), "--id", root.trace_id)
            assert out.returncode == 0, out.stderr
            assert "shard.relay" in out.stdout
            assert "resolve.query" in out.stdout
            assert "zk.op" in out.stdout
            assert root.trace_id in out.stdout

            # zkcli status understands the sharded shape: healthy -> 0.
            out = _run_tool("status", "-f", str(cfg))
            assert out.returncode == 0, out.stderr
            assert "shard 0 up" in out.stderr and "shard 1 up" in out.stderr
            assert "healthy" in out.stderr

            # SIGHUP with a changed shard count reshards in place.
            write_cfg(3)
            proc.send_signal(signal_mod.SIGHUP)
            snapshot = await poll_status(
                lambda s: s.get("serve", {}).get("shards") == 3
                and not s.get("degraded"),
                "reshard to 3 shards",
            )
            assert snapshot["serve"]["generation"] == 1
            assert set(snapshot["shards"]) == {"0", "1", "2"}
        finally:
            if proc is not None and proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(10)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()
            await client.close()
            await server.stop()

    def test_serve_sharded_requires_serve_block(self, tmp_path):
        cfg = tmp_path / "cfg.json"
        cfg.write_text(json.dumps({
            "registration": {"domain": "cli.test.us", "type": "host"},
            "zookeeper": {"servers": [{"host": "127.0.0.1", "port": 1}]},
        }))
        out = _run_tool("serve-sharded", "-f", str(cfg),
                        "--duration", "1")
        assert out.returncode == 2
        assert "serve" in out.stderr


class TestShardedStatus:
    """zkcli status against a sharded /status snapshot: per-shard lines,
    degraded exit when any shard is down (the PR-9 status contract's
    sharded shape)."""

    async def _status_against(self, snapshot, tmp_path):
        from registrar_tpu import metrics as metrics_mod
        from registrar_tpu.tools import zkcli as zkcli_mod

        async def provider():
            return snapshot

        server = metrics_mod.MetricsServer(
            metrics_mod.MetricsRegistry(), status_provider=provider,
        )
        await server.start()
        try:
            cfg = tmp_path / "cfg.json"
            cfg.write_text(json.dumps({
                "registration": {"domain": "a.b.c", "type": "host"},
                "zookeeper": {
                    "servers": [{"host": "127.0.0.1", "port": 1}]
                },
                "metrics": {"port": server.port},
            }))

            class Args:
                file = str(cfg)
                timeout = 5.0

            return await zkcli_mod._cmd_status(Args())
        finally:
            await server.stop()

    def _snapshot(self, *, down=()):
        shards = {}
        for sid in ("0", "1"):
            shards[sid] = {
                "up": sid not in down,
                "respawns": 0,
                "resolves_total": 10,
                "entries": 4,
                "authoritative": sid not in down,
                "coherence_lag_ms_last": 0.5,
                "session": {"id": "0xabc", "connected": True,
                            "readOnly": False,
                            "server": "127.0.0.1:2181"},
            }
        return {
            "serve": {"shards": 2, "generation": 0, "reshards": 0,
                      "respawns_total": 0},
            "degraded": bool(down),
            "shards_down": [int(s) for s in down],
            "shards": shards,
            "uptime_s": 12.0,
            "last_transition": {},
        }

    async def test_healthy_sharded_snapshot_exits_zero(self, tmp_path, capsys):
        assert await self._status_against(self._snapshot(), tmp_path) == 0

    async def test_down_shard_is_degraded(self, tmp_path, capsys):
        rc = await self._status_against(
            self._snapshot(down=("1",)), tmp_path
        )
        assert rc == 1
        err = capsys.readouterr().err
        assert "shard 1 down" in err and "DEGRADED" in err
