"""Chroot support: the connect-string "/app" suffix of standard ZK clients.

A chrooted client sends every path prefixed and sees every returned path
(created paths, sync, watch events, multi results) stripped.  The chroot
node itself is never auto-created — like the Apache client and kazoo.
The reference never chroots (zkplus had no such option), so this is
transport surface beyond parity; default (no chroot) behavior is pinned
unchanged by the rest of the suite.
"""

import asyncio

import pytest

from registrar_tpu.config import ConfigError, parse_config
from registrar_tpu.testing.server import ZKServer
from registrar_tpu.zk.client import Op, ZKClient
from registrar_tpu.zk.protocol import CreateFlag, Err, ZKError


async def _trio():
    """Server + chrooted client (under /app) + unchrooted observer."""
    server = await ZKServer().start()
    observer = await ZKClient([server.address]).connect()
    await observer.mkdirp("/app")
    client = await ZKClient([server.address], chroot="/app").connect()
    return server, client, observer


class TestChrootOps:
    async def test_paths_map_both_ways(self):
        server, client, observer = await _trio()
        try:
            created = await client.create("/x", b"v")
            assert created == "/x"  # stripped on the way back
            assert (await observer.get("/app/x"))[0] == b"v"  # prefixed

            await client.put("/deep/node", b"d")  # mkdirp fallback path
            assert (await observer.get("/app/deep/node"))[0] == b"d"

            assert await client.get_children("/") == ["deep", "x"]
            assert (await client.stat("/x")).data_length == 1
            assert await client.sync("/x") == "/x"

            await client.unlink("/x")
            assert await observer.exists("/app/x") is None

            # root of the chroot is the chroot node itself
            assert (await client.stat("/")).czxid == (
                await observer.stat("/app")
            ).czxid
        finally:
            await client.close()
            await observer.close()
            await server.stop()

    async def test_get_many_under_chroot(self):
        # get_many posts its own pipelined frames (it does not go
        # through get()), so its _abs translation needs its own pin.
        server, client, observer = await _trio()
        try:
            await client.create("/gm1", b"one")
            await client.create("/gm2", b"two")
            results = await client.get_many(["/gm1", "/missing", "/gm2"])
            assert results[0][0] == b"one"
            assert results[1] is None
            assert results[2][0] == b"two"
            # the frames really carried the chroot-prefixed paths
            assert (await observer.get("/app/gm1"))[0] == b"one"
        finally:
            await client.close()
            await observer.close()
            await server.stop()

    async def test_ephemeral_and_acl_ops_under_chroot(self):
        from registrar_tpu.zk.protocol import OPEN_ACL_UNSAFE

        server, client, observer = await _trio()
        try:
            await client.create("/eph", b"", CreateFlag.EPHEMERAL)
            st = await observer.stat("/app/eph")
            assert st.ephemeral_owner == client.session_id

            acls, stat = await client.get_acl("/eph")
            assert acls == list(OPEN_ACL_UNSAFE)
            await client.set_acl("/eph", list(OPEN_ACL_UNSAFE))
            assert (await observer.stat("/app/eph")).aversion == 1
        finally:
            await client.close()
            await observer.close()
            await server.stop()

    async def test_multi_paths_mapped(self):
        server, client, observer = await _trio()
        try:
            results = await client.multi(
                [Op.create("/t", b""), Op.create("/t/a", b"x")]
            )
            assert results == ["/t", "/t/a"]  # stripped in results
            assert await observer.exists("/app/t/a") is not None
        finally:
            await client.close()
            await observer.close()
            await server.stop()

    async def test_missing_chroot_node_is_no_node(self):
        # Like real clients: nothing auto-creates the chroot.
        server = await ZKServer().start()
        client = await ZKClient([server.address], chroot="/nowhere").connect()
        try:
            with pytest.raises(ZKError) as exc:
                await client.create("/x", b"")
            assert exc.value.code == Err.NO_NODE
        finally:
            await client.close()
            await server.stop()

    async def test_invalid_chroot_rejected(self):
        with pytest.raises(ValueError):
            ZKClient([("h", 1)], chroot="no-slash")
        with pytest.raises(ValueError):
            ZKClient([("h", 1)], chroot="/trailing/")
        # "/" and "" mean no chroot
        assert ZKClient([("h", 1)], chroot="/").chroot == ""
        assert ZKClient([("h", 1)], chroot=None).chroot == ""


class TestChrootWatches:
    async def test_watch_events_arrive_in_client_coordinates(self):
        server, client, observer = await _trio()
        try:
            await client.create("/w", b"1")
            events = []
            got = asyncio.Event()

            def listen(ev):
                events.append(ev)
                got.set()

            client.watch("/w", listen)
            await client.get("/w", watch=True)
            await observer.put("/app/w", b"2")  # change via absolute path
            await asyncio.wait_for(got.wait(), timeout=10)
            assert events[0].path == "/w"  # stripped
        finally:
            await client.close()
            await observer.close()
            await server.stop()

    async def test_watches_rearm_after_reconnect_under_chroot(self):
        server, client, observer = await _trio()
        try:
            await client.create("/w", b"1")
            await client.get("/w", watch=True)
            got = asyncio.Event()
            client.watch("/w", lambda ev: got.set())

            await server.drop_connections()
            # Change the node while the chrooted client is reconnecting;
            # SetWatches catch-up must deliver the missed event, with the
            # path back in client coordinates.  (The observer was dropped
            # too — retry its write through its own reconnect.)
            deadline = asyncio.get_running_loop().time() + 15
            while True:
                try:
                    await observer.put("/app/w", b"2")
                    break
                except ZKError as err:
                    if err.code != Err.CONNECTION_LOSS:
                        raise
                    assert asyncio.get_running_loop().time() < deadline
                    await asyncio.sleep(0.05)
            await asyncio.wait_for(got.wait(), timeout=15)
        finally:
            await client.close()
            await observer.close()
            await server.stop()


class TestChrootRegistration:
    async def test_full_registration_under_chroot(self):
        """The whole pipeline runs in chroot coordinates; Binder (reading
        the same chroot) sees the standard layout under the prefix."""
        from registrar_tpu.registration import register

        server, client, observer = await _trio()
        try:
            nodes = await register(
                client,
                {"domain": "chroot.test.us", "type": "host"},
                admin_ip="10.3.3.3",
                hostname="cbox",
                settle_delay=0,
            )
            assert nodes == ["/us/test/chroot/cbox"]
            data, st = await observer.get("/app/us/test/chroot/cbox")
            assert st.ephemeral_owner == client.session_id
            assert b"10.3.3.3" in data
        finally:
            await client.close()
            await observer.close()
            await server.stop()


class TestChrootConfig:
    def test_parse_and_normalize(self):
        base = {
            "registration": {"domain": "a.b", "type": "host"},
            "zookeeper": {
                "servers": [{"host": "h", "port": 1}], "chroot": "/app",
            },
        }
        assert parse_config(base).zookeeper.chroot == "/app"

        base["zookeeper"]["chroot"] = "/"
        assert parse_config(base).zookeeper.chroot is None

        for bad in ("app", "/app/", 7, "/a//b", "/a/../b"):
            base["zookeeper"]["chroot"] = bad
            with pytest.raises(ConfigError):
                parse_config(base)
