"""Tests for the in-tree static checker behind ``make check``.

The reference's lint gate (jsl + jsstyle, its Makefile:15,18) fails the
build on an undefined name or unused variable; these tests pin the same
property for tools/check.py, per the round-1 review's acceptance
criterion: injecting an unused import or undefined name must fail the
gate.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHECKER = os.path.join(REPO, "tools", "check.py")

sys.path.insert(0, os.path.join(REPO, "tools"))
import check  # noqa: E402  (the module under test)


def run_checker(*paths):
    return subprocess.run(
        [sys.executable, CHECKER, *paths],
        capture_output=True,
        text=True,
        cwd=REPO,  # default targets are repo-root-relative
    )


def problems(source, tmp_path, name="mod.py"):
    path = tmp_path / name
    path.write_text(source)
    return [msg for _line, msg in check.check_file(str(path))]


def test_repo_is_clean():
    proc = run_checker()  # default targets, run from the repo root
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_unused_import_fails_gate(tmp_path):
    msgs = problems("import os\nimport sys\nprint(sys.argv)\n", tmp_path)
    assert msgs == ["unused import 'os'"]


def test_undefined_name_fails_gate(tmp_path):
    msgs = problems("def f():\n    return undefined_thing\n", tmp_path)
    assert msgs == ["undefined name 'undefined_thing'"]


def test_gate_exit_code_nonzero(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import os\n")
    proc = run_checker(str(bad))
    assert proc.returncode == 1
    assert "unused import 'os'" in proc.stdout


def test_syntax_error_is_reported(tmp_path):
    msgs = problems("def f(:\n", tmp_path)
    assert len(msgs) == 1 and msgs[0].startswith("syntax error")


@pytest.mark.parametrize(
    "source",
    [
        # __all__ strings count as usage (re-export surface).
        "import os\n__all__ = ['os']\n",
        # explicit re-export convention
        "import os as os\n",
        # used only in a type annotation (kept as AST under
        # `from __future__ import annotations` too)
        "from __future__ import annotations\nimport typing\n"
        "def f(x: typing.Any): return x\n",
        # conditional import fallback
        "try:\n    import json\nexcept ImportError:\n    json = None\n"
        "print(json)\n",
    ],
)
def test_import_usage_patterns_pass(source, tmp_path):
    assert problems(source, tmp_path) == []


@pytest.mark.parametrize(
    "source",
    [
        # comprehension target is local to the comprehension
        "xs = [i for i in range(3)]\nprint(xs)\n",
        # walrus binds in the enclosing function scope
        "def f(v):\n    if (n := len(v)) > 1:\n        return n\n",
        # global statement binds at module level
        "def f():\n    global counter\n    counter = 1\n"
        "def g():\n    return counter\n",
        # class attributes are not visible in methods (self.x is fine)
        "class C:\n    x = 1\n    def m(self):\n        return self.x\n",
        # except ... as e binds
        "try:\n    pass\nexcept ValueError as e:\n    print(e)\n",
        # tuple-unpacking for-loop targets bind both names
        "def f(x):\n    for k, v in x.items():\n        yield k, v\n",
        # decorators and defaults
        "import functools\n@functools.wraps(print)\ndef f(a=len('x')):\n"
        "    return a\n",
        # lambda args
        "f = lambda a, *rest, **kw: (a, rest, kw)\nprint(f(1))\n",
        # del unbinds but is a binding occurrence, not a load
        "x = 1\ndel x\n",
        # nested function sees enclosing bindings
        "def outer():\n    y = 2\n    def inner():\n        return y\n"
        "    return inner\n",
    ],
)
def test_scoping_patterns_pass(source, tmp_path):
    assert problems(source, tmp_path) == []


def test_class_scope_invisible_to_methods(tmp_path):
    msgs = problems(
        "class C:\n    x = 1\n    def m(self):\n        return x\n",
        tmp_path,
    )
    assert msgs == ["undefined name 'x'"]


def test_star_import_disables_undefined_check(tmp_path):
    assert problems("from os.path import *\nprint(join('a'))\n", tmp_path) == []


@pytest.mark.skipif(
    sys.version_info < (3, 10), reason="match statements need 3.10+"
)
def test_match_capture_patterns_bind(tmp_path):
    source = (
        "def f(x):\n"
        "    match x:\n"
        "        case {'k': v, **rest}:\n"
        "            return v, rest\n"
        "        case [head, *tail]:\n"
        "            return head, tail\n"
        "        case other:\n"
        "            return other\n"
    )
    assert problems(source, tmp_path) == []


def test_missing_target_fails_gate(tmp_path):
    proc = run_checker(str(tmp_path / "does_not_exist.py"))
    assert proc.returncode == 2
    assert "does not exist" in proc.stderr
