"""Tests for the in-tree static analysis suite behind ``make check``.

The reference's lint gate (jsl + jsstyle, its Makefile:15,18) fails the
build on a flagged construct; these tests pin the same property for
``tools/check.py`` + ``tools/checklib/``, mutation-style: for EVERY
registered rule, injecting its seeded violation into a scratch package
tree must fail the gate, and the suppression/baseline machinery must
round-trip (suppress with justification -> pass; baseline -> pass;
baseline entry goes stale -> fail).

Note all violation fixtures live in *string literals*: the suppression
scanner is tokenize-based precisely so directive text inside strings
(like this file's fixtures) is never mistaken for a live suppression.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHECKER = os.path.join(REPO, "tools", "check.py")

sys.path.insert(0, os.path.join(REPO, "tools"))
import check  # noqa: E402  (the module under test)


def run_checker(*args, cwd=REPO):
    return subprocess.run(
        [sys.executable, CHECKER, *args],
        capture_output=True,
        text=True,
        cwd=cwd,
    )


def problems(source, tmp_path, rel_path="mod.py"):
    """Rule findings for one source blob; ``rel_path`` under
    ``registrar_tpu/`` arms the package-scoped rules."""
    path = tmp_path / "mod.py"
    path.write_text(textwrap.dedent(source))
    return check.check_file(str(path), rel_path=rel_path)


def messages(source, tmp_path, rel_path="mod.py"):
    return [f.message for f in problems(source, tmp_path, rel_path)]


def rules_fired(source, tmp_path, rel_path="mod.py"):
    return sorted({f.rule for f in problems(source, tmp_path, rel_path)})


def seed_package_tree(tmp_path, source):
    """A scratch tree whose file sits under registrar_tpu/ (so every
    rule, including the package-scoped ones, is armed when the checker
    runs from the tree root)."""
    pkg = tmp_path / "registrar_tpu"
    pkg.mkdir(exist_ok=True)
    (pkg / "seeded.py").write_text(textwrap.dedent(source))
    return tmp_path


# --- the gate itself ---------------------------------------------------------


def test_repo_is_clean():
    proc = run_checker()  # default targets + shipped baseline, repo root
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_shipped_baseline_is_near_empty():
    # The acceptance bar: at most 3 grandfathered findings may ride in
    # the checked-in baseline; new code must never add to it.
    with open(os.path.join(REPO, "tools", "check-baseline.json")) as fh:
        data = json.load(fh)
    assert data["version"] == 1
    assert len(data["findings"]) <= 3


def test_missing_target_fails_gate(tmp_path):
    proc = run_checker(str(tmp_path / "does_not_exist.py"))
    assert proc.returncode == 2
    assert "does not exist" in proc.stderr


def test_list_rules_names_every_rule():
    proc = run_checker("--list-rules")
    assert proc.returncode == 0
    for rule in EXPECTED_RULES:
        assert rule in proc.stdout


# --- seeded violations: every rule must be live ------------------------------

#: rule -> a minimal source blob that violates exactly that rule.
SEEDED_VIOLATIONS = {
    "undefined-name": """\
        def f():
            return undefined_thing
        """,
    "unused-import": """\
        import os
        import sys
        print(sys.argv)
        """,
    "unawaited-coroutine": """\
        import asyncio

        async def work():
            await asyncio.sleep(0)

        async def main():
            work()
        """,
    "dropped-task": """\
        import asyncio

        async def main(coro):
            asyncio.create_task(coro)
        """,
    "blocking-call-in-async": """\
        import time

        async def main():
            time.sleep(1)
        """,
    "swallowed-cancel": """\
        async def main(fn):
            try:
                await fn()
            except BaseException:
                pass
        """,
    "unguarded-private-attr": """\
        def reap(proc):
            return proc._transport
        """,
    "mutable-default": """\
        def f(items=[]):
            return items
        """,
    "assert-in-package": """\
        def f(x):
            assert x > 0
            return x
        """,
    "syntax-error": """\
        def f(:
        """,
}

#: whole-program rule -> {rel path: source} for a minimal tree that
#: violates exactly that rule (the generation-2 analogs of
#: SEEDED_VIOLATIONS; multi-file because the rules are cross-module).
PROGRAM_SEEDED_VIOLATIONS = {
    "cross-module-unawaited": {
        "registrar_tpu/util.py": """\
            import asyncio

            async def notify():
                await asyncio.sleep(0)
            """,
        "registrar_tpu/seeded.py": """\
            from registrar_tpu import util

            async def main():
                util.notify()
            """,
    },
    "transitive-blocking-call": {
        "registrar_tpu/util.py": """\
            import time

            def pause():
                time.sleep(1)
            """,
        "registrar_tpu/seeded.py": """\
            from registrar_tpu import util

            async def main():
                util.pause()
            """,
    },
    "await-in-lock-free-mutator": {
        "registrar_tpu/registration.py": """\
            async def rewrite(zk):
                await zk.set_data("/a", b"x")
            """,
        "registrar_tpu/agent.py": """\
            from registrar_tpu import registration

            async def repair(zk):
                await registration.rewrite(zk)
            """,
    },
    "dead-event-name": {
        "registrar_tpu/seeded.py": """\
            def fire(ee):
                ee.emit("registered", 1)
            """,
    },
    "unknown-event-name": {
        "registrar_tpu/seeded.py": """\
            def wire(ee):
                ee.on("registered", print)
            """,
    },
    "secret-flow-to-log": {
        "registrar_tpu/seeded.py": """\
            import logging

            log = logging.getLogger("registrar")

            def announce(state):
                log.info("resuming session with %r", state.passwd)
            """,
    },
    "config-key-drift": {
        "registrar_tpu/config.py": """\
            def parse(raw):
                return raw.get("ghostKey")
            """,
        "docs/CONFIG.md": """\
            | Key | Meaning |
            |---|---|
            | `timeout` | documented but unread |
            """,
        "etc/config.example.json": """\
            {"exampleOnly": 1}
            """,
    },
    # -- generation 3: exception-flow rules (ISSUE 7) --
    "retry-contract-drift": {
        "registrar_tpu/retry.py": """\
            def is_transient(err):
                return isinstance(err, ConnectionError)


            async def call_with_backoff(fn, retryable=None):
                return await fn()
            """,
        "registrar_tpu/seeded.py": """\
            from registrar_tpu.retry import call_with_backoff, is_transient


            class QuotaError(Exception):
                pass


            async def push(payload):
                if not payload:
                    raise QuotaError()
                return payload


            async def main(payload):
                return await call_with_backoff(
                    lambda: push(payload), retryable=is_transient
                )
            """,
    },
    "task-exception-blackhole": {
        "registrar_tpu/seeded.py": """\
            import asyncio


            class DropError(Exception):
                pass


            async def pump():
                raise DropError("queue gone")


            def start(tasks):
                t = asyncio.create_task(pump())
                tasks.add(t)
                t.add_done_callback(tasks.discard)
            """,
    },
    "overbroad-handler": {
        "registrar_tpu/errs.py": """\
            class SessionExpiredError(Exception):
                pass
            """,
        "registrar_tpu/store.py": """\
            from registrar_tpu.errs import SessionExpiredError


            async def refresh(tree):
                if tree is None:
                    raise SessionExpiredError()
                return tree
            """,
        "registrar_tpu/seeded.py": """\
            import logging

            from registrar_tpu import store
            from registrar_tpu.errs import SessionExpiredError

            log = logging.getLogger("seeded")


            async def tick(tree):
                try:
                    return await store.refresh(tree)
                except Exception:
                    log.info("refresh failed")
                    return None


            async def drive(tree):
                try:
                    return await tick(tree)
                except SessionExpiredError:
                    return None
            """,
    },
    "fault-matrix-drift": {
        "registrar_tpu/seeded.py": "x = 1\n",
        "docs/FAULTS.md": """\
            # Faults

            On a half-open reply the client raises `GhostTimeoutError`
            and reconnects.
            """,
    },
    "fault-id-drift": {
        "registrar_tpu/seeded.py": """\
            def storm(harness):
                harness.inject("ghost-fault")
            """,
        "docs/FAULTS.md": """\
            # Faults

            | Fault class | injected |
            |---|---|
            | `id: real-fault` | the documented one |
            """,
    },
    "bench-metric-drift": {
        "registrar_tpu/seeded.py": "x = 1\n",
        "bench.py": """\
            BENCH_METRICS = {
                "ghost_metric_ms": "lower",
                "shared_metric_ms": "lower",
            }
            """,
        "BENCH_HISTORY.json": """\
            {"directions": {"shared_metric_ms": "lower",
                            "orphaned_metric_ms": "lower"},
             "rounds": []}
            """,
        "docs/PERF.md": """\
            # Perf

            | metric | value |
            |---|---|
            | shared_metric_ms | fine |
            | phantom_metric_ms | cited but nonexistent |
            """,
    },
    "span-name-drift": {
        "registrar_tpu/seeded.py": """\
            class _Recorder:
                def event(self, name, **attrs):
                    self.last = name


            def note(rec):
                rec.event("agent.ghost_step", detail=1)
            """,
        "docs/OBSERVABILITY.md": """\
            # Observability

            | span | meaning |
            |------|---------|
            | `agent.real_step` | the documented one |
            """,
    },
    "metric-name-drift": {
        "registrar_tpu/metrics.py": """\
            class Counter:
                def __init__(self, name):
                    self.name = name


            def build():
                return Counter("registrar_beats_total")
            """,
        "docs/OPERATIONS.md": """\
            # Operating

            Alert when `registrar_heartbeats_total` stops increasing.
            """,
    },
    # --- generation 4 (ISSUE 15) ---------------------------------------------
    "lock-order-cycle": {
        "registrar_tpu/agent.py": """\
            import asyncio

            repair_lock = asyncio.Lock()
            state_lock = asyncio.Lock()


            async def repair():
                async with repair_lock:
                    await _flush()


            async def _flush():
                async with state_lock:
                    pass


            async def snapshot():
                async with state_lock:
                    async with repair_lock:
                        pass
            """,
    },
    "zk-op-under-lock": {
        "registrar_tpu/agent.py": """\
            import asyncio

            from registrar_tpu.zk.client import connect_with_backoff

            repair_lock = asyncio.Lock()


            async def reconnect_and_repair(zk):
                async with repair_lock:
                    await connect_with_backoff(zk)
            """,
        "registrar_tpu/zk/client.py": """\
            async def connect_with_backoff(zk):
                await zk.connect()
                return zk
            """,
    },
    "leaked-resource": {
        "registrar_tpu/netem.py": """\
            class ChaosProxy:
                def __init__(self, addr):
                    self.addr = addr

                async def start(self):
                    return self

                async def stop(self):
                    self.addr = None


            async def probe(addr):
                proxy = await ChaosProxy(addr).start()
                return addr
            """,
    },
    "span-never-finished": {
        "registrar_tpu/probe.py": """\
            def sample(tracer):
                span = tracer.start_span("probeop")
                return 7
            """,
    },
    "struct-format-drift": {
        "registrar_tpu/shard.py": """\
            import struct

            _HDR = struct.Struct(">IB")


            def parse(buf):
                req_id, op, extra = _HDR.unpack(buf)
                return req_id, op, extra
            """,
    },
    "opcode-dispatch-drift": {
        "registrar_tpu/shard.py": """\
            OP_RESOLVE = 1
            OP_STATUS = 2


            def dispatch(op):
                if op == OP_RESOLVE:
                    return "resolve"
                return None
            """,
    },
    "flag-bit-overlap": {
        "registrar_tpu/shard.py": """\
            TRACE_FLAG = 0x80
            PRIORITY_FLAG = 0xC0
            """,
    },
    # --- generation 5 (ISSUE 16) ---
    "unbounded-peer-allocation": {
        "registrar_tpu/shard.py": """\
            import struct


            def parse(frame):
                (count,) = struct.unpack(">I", frame[:4])
                return b"\\x00" * count
            """,
    },
    "unvalidated-count-loop": {
        "registrar_tpu/zk/jute.py": """\
            import struct

            _INT = struct.Struct(">i")


            class Reader:
                def __init__(self, data):
                    self._data = data
                    self._pos = 0

                def read_int(self):
                    (value,) = _INT.unpack_from(self._data, self._pos)
                    self._pos += 4
                    return value
            """,
        "registrar_tpu/seeded.py": """\
            def load_items(r):
                n = r.read_int()
                return [r.read_int() for _ in range(n)]
            """,
    },
    "unchecked-peer-read-size": {
        "registrar_tpu/shard.py": """\
            import struct


            async def read_frame(reader):
                head = await reader.readexactly(4)
                (size,) = struct.unpack(">I", head)
                return await reader.readexactly(size)
            """,
    },
    "taint-boundary-drift": {
        "registrar_tpu/shard.py": """\
            import struct


            def parse(frame):
                (count,) = struct.unpack(">I", frame[:4])
                if count > 64:
                    raise ValueError("count too large")
                return b"\\x00" * count
            """,
        "docs/DESIGN.md": """\
            # Design

            ## Appendix: trust boundary (taint sources and sinks)

            | Pattern | Role | Module | Meaning |
            |---|---|---|---|
            | `read_int` | source | `registrar_tpu/zk/jute.py` | stale row |
            | `bytes` | sink | — | allocation sized by arg |
            | `bytearray` | sink | — | allocation sized by arg |
            | `range` | sink | — | loop bound |
            | `readexactly` | sink | — | stream read size |
            | `_take` | sink | — | buffer carve size |
            | `_skip` | sink | — | buffer skip size |
            | `slice` | sink | — | slice bound |
            | `sequence-repeat` | sink | — | repeat count |
            | `recursion` | sink | — | tainted self-recursion |
            """,
    },
    "stale-read-across-await": {
        "registrar_tpu/agent.py": """\
            import asyncio

            repair_lock = asyncio.Lock()


            async def guarded(ee):
                async with repair_lock:
                    ee.count = 0


            async def bump(ee):
                snap = ee.count
                await asyncio.sleep(0)
                ee.count = snap + 1
            """,
    },
}

EXPECTED_RULES = sorted(
    (set(SEEDED_VIOLATIONS) - {"syntax-error"})
    | set(PROGRAM_SEEDED_VIOLATIONS)
)


def test_every_registered_rule_has_a_seeded_violation():
    from checklib.registry import RULES

    assert sorted(RULES) == EXPECTED_RULES


@pytest.mark.parametrize("rule", sorted(SEEDED_VIOLATIONS))
def test_seeded_violation_fails_gate(rule, tmp_path):
    """Mutation-style: inject the violation into a scratch package tree
    and the full gate (subprocess, exit code) must fail on that rule."""
    tree = seed_package_tree(tmp_path, SEEDED_VIOLATIONS[rule])
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert f"[{rule}]" in proc.stdout


@pytest.mark.parametrize("rule", sorted(SEEDED_VIOLATIONS))
def test_seeded_violation_is_the_only_finding(rule, tmp_path):
    fired = rules_fired(
        SEEDED_VIOLATIONS[rule], tmp_path, rel_path="registrar_tpu/seeded.py"
    )
    assert fired == [rule]


# --- rule-specific positives and negatives -----------------------------------


def test_unawaited_self_method(tmp_path):
    src = """\
        import asyncio

        class C:
            async def flush(self):
                await asyncio.sleep(0)

            async def run(self):
                self.flush()
        """
    assert rules_fired(src, tmp_path) == ["unawaited-coroutine"]


def test_unawaited_in_function_local_class(tmp_path):
    # Class context must survive into function bodies: a class defined
    # inside a def carries its async methods for self-call resolution.
    src = """\
        import asyncio

        def make():
            class Foo:
                async def work(self):
                    await asyncio.sleep(0)

                async def other(self):
                    self.work()

            return Foo
        """
    assert rules_fired(src, tmp_path) == ["unawaited-coroutine"]


def test_awaited_coroutine_passes(tmp_path):
    src = """\
        import asyncio

        async def work():
            await asyncio.sleep(0)

        async def main():
            await work()
            t = asyncio.create_task(work())
            await t
        """
    assert rules_fired(src, tmp_path) == []


def test_dropped_task_loop_variant(tmp_path):
    src = """\
        import asyncio

        def main(coro):
            loop = asyncio.get_event_loop()
            loop.create_task(coro)
        """
    assert rules_fired(src, tmp_path) == ["dropped-task"]


def test_dropped_task_call_rooted_receiver(tmp_path):
    # The repo's own idiom: the receiver chain is rooted in a call, so
    # plain dotted-name matching would miss it (the events.py:52 bug
    # this rule's hardening caught for real).
    src = """\
        import asyncio

        def main(coro):
            asyncio.get_running_loop().create_task(coro)
        """
    assert rules_fired(src, tmp_path) == ["dropped-task"]


def test_shadowed_async_name_not_flagged(tmp_path):
    # `notify` is also a parameter somewhere in the file: without scope
    # resolution the bare call is ambiguous, and a build gate must not
    # flag valid code (the sync callable passed in wins at runtime).
    src = """\
        import asyncio

        async def notify():
            await asyncio.sleep(0)

        def fire(notify):
            notify()
        """
    assert rules_fired(src, tmp_path) == []


def test_sync_def_shadowing_async_name_not_flagged(tmp_path):
    # A sync def (or class) of the same name also makes the bare call
    # ambiguous — the later definition wins at module level.
    src = """\
        import asyncio

        async def notify():
            await asyncio.sleep(0)

        def notify():
            return 1

        def fire():
            notify()
        """
    assert rules_fired(src, tmp_path) == []


def test_taskgroup_spawn_not_flagged(tmp_path):
    # TaskGroup owns the tasks it spawns (it awaits them at block exit);
    # discarding tg.create_task's handle is the canonical 3.11+ idiom,
    # not a GC hazard — flagging it would fail the gate on correct code.
    src = """\
        import asyncio

        async def main():
            async with asyncio.TaskGroup() as tg:
                tg.create_task(asyncio.sleep(0))
        """
    assert rules_fired(src, tmp_path) == []


def test_tracked_task_passes(tmp_path):
    src = """\
        import asyncio

        tasks = set()

        def main(coro):
            task = asyncio.create_task(coro)
            tasks.add(task)
            task.add_done_callback(tasks.discard)
        """
    assert rules_fired(src, tmp_path) == []


def test_blocking_open_write_in_async(tmp_path):
    src = """\
        async def save(data):
            with open("/tmp/state", "w") as fh:
                fh.write(data)
        """
    fired = rules_fired(src, tmp_path, rel_path="registrar_tpu/mod.py")
    assert fired == ["blocking-call-in-async"]


def test_blocking_call_fine_in_sync_and_outside_package(tmp_path):
    src = """\
        import time

        def pause():
            time.sleep(1)

        async def main():
            def helper():
                time.sleep(1)
            return helper
        """
    # sync contexts never flag; and even an async blocking call is a
    # package-scoped concern (tests/tools legitimately block)
    assert rules_fired(src, tmp_path, rel_path="registrar_tpu/mod.py") == []
    blocking = SEEDED_VIOLATIONS["blocking-call-in-async"]
    assert rules_fired(blocking, tmp_path, rel_path="tests/mod.py") == []


def test_open_read_in_async_passes(tmp_path):
    src = """\
        async def load():
            with open("/etc/config.json") as fh:
                return fh.read()
        """
    assert rules_fired(src, tmp_path, rel_path="registrar_tpu/mod.py") == []


def test_cancel_reraise_and_reap_idioms_pass(tmp_path):
    src = """\
        import asyncio

        async def loop_body(fn, task):
            try:
                await fn()
            except asyncio.CancelledError:
                raise
            except Exception:
                pass
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
        """
    assert rules_fired(src, tmp_path) == []


def test_explicit_cancel_swallow_on_work_flags(tmp_path):
    src = """\
        import asyncio

        async def main(fn):
            try:
                await fn()
            except asyncio.CancelledError:
                pass
        """
    assert rules_fired(src, tmp_path) == ["swallowed-cancel"]


def test_bare_except_flags_even_in_sync(tmp_path):
    src = """\
        def f(fn):
            try:
                fn()
            except:
                pass
        """
    assert rules_fired(src, tmp_path) == ["swallowed-cancel"]


def test_getattr_guard_passes(tmp_path):
    src = """\
        def reap(proc):
            transport = getattr(proc, "_transport", None)
            if transport is not None:
                transport.close()
        """
    assert rules_fired(src, tmp_path, rel_path="registrar_tpu/mod.py") == []


def test_same_module_private_attr_passes(tmp_path):
    src = """\
        class Conn:
            def __init__(self):
                self._outbuf = []

        def flush_all(conns):
            return [c._outbuf for c in conns]
        """
    assert rules_fired(src, tmp_path, rel_path="registrar_tpu/mod.py") == []


def test_private_attr_fine_outside_package(tmp_path):
    src = SEEDED_VIOLATIONS["unguarded-private-attr"]
    assert rules_fired(src, tmp_path, rel_path="tests/mod.py") == []


def test_mutable_default_variants(tmp_path):
    src = """\
        def f(a={}, *, b=set()):
            return a, b
        """
    findings = problems(src, tmp_path)
    assert [f.rule for f in findings] == ["mutable-default"] * 2


def test_mutable_default_on_lambda(tmp_path):
    src = """\
        handler = lambda ev, seen=[]: seen.append(ev)
        print(handler)
        """
    findings = problems(src, tmp_path)
    assert [f.rule for f in findings] == ["mutable-default"]
    assert "'<lambda>()'" in findings[0].message


def test_none_default_passes(tmp_path):
    src = """\
        def f(a=None, b=(), c="x", d=0):
            return a, b, c, d
        """
    assert rules_fired(src, tmp_path) == []


def test_assert_fine_outside_package(tmp_path):
    src = SEEDED_VIOLATIONS["assert-in-package"]
    assert rules_fired(src, tmp_path, rel_path="tests/mod.py") == []


# --- suppression machinery ---------------------------------------------------


def test_suppression_with_justification_passes_gate(tmp_path):
    src = """\
        def f(x):
            assert x  # check: disable=assert-in-package -- fixture, not shipped logic
            return x
        """
    assert rules_fired(src, tmp_path, rel_path="registrar_tpu/mod.py") == []


def test_standalone_suppression_covers_next_line(tmp_path):
    src = """\
        def f(x):
            # check: disable=assert-in-package -- covered by the gate test below
            assert x
            return x
        """
    assert rules_fired(src, tmp_path, rel_path="registrar_tpu/mod.py") == []


def test_suppression_without_justification_is_a_finding(tmp_path):
    src = """\
        def f(x):
            assert x  # check: disable=assert-in-package
            return x
        """
    fired = rules_fired(src, tmp_path, rel_path="registrar_tpu/mod.py")
    # the malformed comment is flagged AND the violation still fires
    assert fired == ["assert-in-package", "bad-suppression"]


def test_suppression_of_unknown_rule_is_a_finding(tmp_path):
    src = """\
        x = 1  # check: disable=no-such-rule -- because
        """
    assert rules_fired(src, tmp_path) == ["bad-suppression"]


def test_unused_suppression_is_a_finding(tmp_path):
    src = """\
        x = 1  # check: disable=mutable-default -- nothing here to excuse
        """
    assert rules_fired(src, tmp_path) == ["unused-suppression"]


def test_stale_rule_in_multi_rule_suppression_reported(tmp_path):
    # `disable=a,b` where only `a` matches: the suppression works for
    # `a` but the stale `b` must still be flagged — per-rule tracking,
    # not per-directive.
    src = """\
        def f(items=[]):  # check: disable=mutable-default,unawaited-coroutine -- partial fixture
            return items
        """
    fired = rules_fired(src, tmp_path)
    assert fired == ["unused-suppression"]


def test_engine_rule_in_suppression_is_bad_suppression(tmp_path):
    # Engine findings are not suppressible; naming one must say so
    # rather than surfacing later as a baffling unused-suppression.
    src = """\
        x = 1  # check: disable=syntax-error -- cannot work
        """
    findings = problems(src, tmp_path)
    assert [f.rule for f in findings] == ["bad-suppression"]
    assert "cannot be suppressed" in findings[0].message


def test_suppression_only_silences_named_rule(tmp_path):
    src = """\
        def f(items=[]):  # check: disable=assert-in-package -- wrong rule named
            return items
        """
    fired = rules_fired(src, tmp_path, rel_path="registrar_tpu/mod.py")
    # the mutable default still fires; the mistargeted suppression is unused
    assert fired == ["mutable-default", "unused-suppression"]


def test_suppression_survives_form_feed_above_it(tmp_path):
    # str.splitlines() splits on \f (and \v, \x1c, U+2028) where ast and
    # tokenize do not; a form feed — a common section separator — above
    # a suppression must not skew its line binding (the scanner splits
    # on '\n' only).
    src = (
        "x = 1\n"
        "\f\n"
        "import os  # check: disable=unused-import -- form-feed fixture\n"
    )
    path = tmp_path / "mod.py"
    path.write_text(src)
    assert [f.rule for f in check.check_file(str(path))] == []


def test_package_scope_disarm_regression(tmp_path):
    # The concrete regression: package-scoped rules key off rel paths
    # anchored at the CHECKER's repo root, so a cwd-relative invocation
    # from inside registrar_tpu/ must still arm them.  Reproduced in a
    # scratch copy of tools/ (its own repo root) rather than by seeding
    # a file into the live tree — a parallel test run or a hard kill
    # mid-test must never be able to fail the real gate.
    import shutil

    shutil.copytree(os.path.join(REPO, "tools"), tmp_path / "tools")
    pkg = tmp_path / "registrar_tpu"
    pkg.mkdir()
    (pkg / "seeded.py").write_text(
        "import time\n\nasync def main():\n    time.sleep(1)\n"
    )
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join("..", "tools", "check.py"),
            "seeded.py",
            "--no-baseline",
        ],
        capture_output=True,
        text=True,
        cwd=pkg,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "[blocking-call-in-async]" in proc.stdout
    assert "registrar_tpu/seeded.py" in proc.stdout


def test_standalone_suppression_covers_wrapped_statement(tmp_path):
    # A finding anchored on a *continuation* line (the wrapped default
    # argument) must still be covered by a suppression above the
    # statement — and must not be double-reported as the violation PLUS
    # an unused-suppression.
    src = """\
        # check: disable=mutable-default -- wrapped-signature fixture
        def f(
            items=[],
        ):
            return items
        """
    assert rules_fired(src, tmp_path) == []


def test_standalone_suppression_covers_decorated_def(tmp_path):
    # Above a decorated def, the comment's target resolves to the
    # decorator line; the covered span must still reach the signature
    # (FunctionDef.lineno is the `def` line, not the `@deco` line).
    src = """\
        import functools

        # check: disable=mutable-default -- decorated fixture
        @functools.lru_cache(maxsize=None)
        def f(items=[]):
            return items
        """
    assert rules_fired(src, tmp_path) == []


def test_suppression_above_def_does_not_leak_into_body(tmp_path):
    # The covered span is the compound statement's HEADER only: a
    # comment above the def must not silence findings inside its body.
    src = """\
        # check: disable=assert-in-package -- header-only fixture
        def f(
            x,
        ):
            assert x
            return x
        """
    fired = rules_fired(src, tmp_path, rel_path="registrar_tpu/mod.py")
    assert fired == ["assert-in-package", "unused-suppression"]


def test_empty_rule_list_is_bad_suppression(tmp_path):
    src = """\
        x = 1  # check: disable=, -- oops
        """
    findings = problems(src, tmp_path)
    assert [f.rule for f in findings] == ["bad-suppression"]
    assert "names no rules" in findings[0].message


def test_trailing_suppression_on_continuation_line(tmp_path):
    # A noqa-style comment on the LAST line of a wrapped statement must
    # suppress the finding anchored at the statement's first line.
    src = """\
        import asyncio

        def fire(coro):
            asyncio.ensure_future(
                coro)  # check: disable=dropped-task -- fixture owns it elsewhere
        """
    assert rules_fired(src, tmp_path) == []


def test_decorator_blocking_call_not_flagged_as_async(tmp_path):
    # Decorators/defaults of an async def evaluate at definition time in
    # the enclosing (sync) context — not on the event loop.
    src = """\
        import time

        def throttled(delay):
            def deco(fn):
                return fn
            return deco

        @throttled(time.sleep(0.0) or 1)
        async def f(x=time.sleep(0.0)):
            return x
        """
    assert rules_fired(src, tmp_path, rel_path="registrar_tpu/mod.py") == []


def test_sync_def_in_async_body_defined_on_loop(tmp_path):
    # The inverse: a sync def nested in an async BODY is defined while
    # the async frame runs, so ITS definition-time expressions (the
    # default) do block the loop — but its body does not.
    src = """\
        import time

        async def outer():
            def helper(x=time.sleep(1)):
                time.sleep(1)
                return x
            return helper
        """
    findings = problems(src, tmp_path, rel_path="registrar_tpu/mod.py")
    assert [f.rule for f in findings] == ["blocking-call-in-async"]
    assert findings[0].line == 4  # the default, not the body sleep


def test_directive_inside_string_literal_is_inert(tmp_path):
    src = '''\
        EXAMPLE = "x = 1  # check: disable=mutable-default -- doc example"
        print(EXAMPLE)
        '''
    assert rules_fired(src, tmp_path) == []


# --- baseline machinery ------------------------------------------------------


def test_baseline_round_trip(tmp_path):
    """write-baseline grandfathers the findings; fixing the code without
    shrinking the baseline fails the gate as stale."""
    tree = seed_package_tree(tmp_path, SEEDED_VIOLATIONS["mutable-default"])
    bl = str(tmp_path / "bl.json")

    proc = run_checker("registrar_tpu", "--write-baseline", "--baseline", bl, cwd=tree)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "wrote 1 finding(s)" in proc.stdout

    # grandfathered: the same tree now passes the gate
    proc = run_checker("registrar_tpu", "--baseline", bl, cwd=tree)
    assert proc.returncode == 0, proc.stdout + proc.stderr

    # the violation gets fixed but the baseline entry lingers -> stale
    seed_package_tree(tmp_path, "def f(items=None):\n    return items\n")
    proc = run_checker("registrar_tpu", "--baseline", bl, cwd=tree)
    assert proc.returncode == 1
    assert "[stale-baseline]" in proc.stdout


def test_baseline_does_not_cover_new_findings(tmp_path):
    tree = seed_package_tree(tmp_path, SEEDED_VIOLATIONS["mutable-default"])
    bl = str(tmp_path / "bl.json")
    run_checker("registrar_tpu", "--write-baseline", "--baseline", bl, cwd=tree)

    # a NEW violation of another rule appears: gate must fail on it
    seed_package_tree(
        tmp_path,
        textwrap.dedent(SEEDED_VIOLATIONS["mutable-default"])
        + "\ndef g(x):\n    assert x\n",
    )
    proc = run_checker("registrar_tpu", "--baseline", bl, cwd=tree)
    assert proc.returncode == 1
    assert "[assert-in-package]" in proc.stdout
    assert "[mutable-default]" not in proc.stdout  # still grandfathered


def test_partial_run_does_not_report_unchecked_entries_stale(tmp_path):
    # A baseline entry for a file OUTSIDE the run's targets must not be
    # condemned as stale — single-file invocations are the everyday dev
    # workflow and must work with a populated baseline.
    tree = seed_package_tree(tmp_path, SEEDED_VIOLATIONS["mutable-default"])
    bl = str(tmp_path / "bl.json")
    run_checker("registrar_tpu", "--write-baseline", "--baseline", bl, cwd=tree)
    (tmp_path / "solo.py").write_text("x = 1\n")

    proc = run_checker("solo.py", "--baseline", bl, cwd=tree)
    assert proc.returncode == 0, proc.stdout + proc.stderr

    # ... but a full-tree run still reports true staleness
    seed_package_tree(tmp_path, "def f(items=None):\n    return items\n")
    proc = run_checker("registrar_tpu", "solo.py", "--baseline", bl, cwd=tree)
    assert proc.returncode == 1
    assert "[stale-baseline]" in proc.stdout


def test_deleted_file_baseline_entry_is_stale(tmp_path):
    # The burn-down invariant must survive file deletion: an entry for a
    # file that no longer exists can never be matched OR checked again,
    # so it must fail the gate as stale rather than linger forever.
    tree = seed_package_tree(tmp_path, SEEDED_VIOLATIONS["mutable-default"])
    bl = str(tmp_path / "bl.json")
    run_checker("registrar_tpu", "--write-baseline", "--baseline", bl, cwd=tree)

    os.remove(tmp_path / "registrar_tpu" / "seeded.py")
    (tmp_path / "registrar_tpu" / "clean.py").write_text("x = 1\n")
    proc = run_checker("registrar_tpu", "--baseline", bl, cwd=tree)
    assert proc.returncode == 1
    assert "[stale-baseline]" in proc.stdout
    # '.' as the target must detect the same staleness ('.' normalizes
    # to the everything-in-scope prefix, not a never-matching './')
    proc = run_checker(".", "--baseline", bl, cwd=tree)
    assert proc.returncode == 1
    assert "[stale-baseline]" in proc.stdout


def test_deleted_file_staleness_not_masked_by_repo_collision(tmp_path):
    # A scratch tree's baseline entry whose rel path collides with a
    # file in the checker's OWN repo (registrar_tpu/health.py exists
    # there) must still go stale when the scratch file is deleted: a
    # non-default baseline resolves existence against its own tree only.
    pkg = tmp_path / "registrar_tpu"
    pkg.mkdir()
    (pkg / "health.py").write_text("def f(items=[]):\n    return items\n")
    bl = str(tmp_path / "bl.json")
    run_checker("registrar_tpu", "--write-baseline", "--baseline", bl, cwd=tmp_path)

    os.remove(pkg / "health.py")
    (pkg / "clean.py").write_text("x = 1\n")
    proc = run_checker("registrar_tpu", "--baseline", bl, cwd=tmp_path)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "[stale-baseline]" in proc.stdout


def test_overlapping_targets_check_each_file_once(tmp_path):
    # `check.py registrar_tpu registrar_tpu/seeded.py` must not analyze
    # seeded.py twice: duplicated findings would double-print and defeat
    # the multiset baseline (one entry, two occurrences -> spurious fail).
    tree = seed_package_tree(tmp_path, SEEDED_VIOLATIONS["mutable-default"])
    bl = str(tmp_path / "bl.json")
    run_checker("registrar_tpu", "--write-baseline", "--baseline", bl, cwd=tree)

    overlap = ("registrar_tpu", os.path.join("registrar_tpu", "seeded.py"))
    proc = run_checker(*overlap, "--no-baseline", cwd=tree)
    assert proc.returncode == 1
    assert proc.stdout.count("[mutable-default]") == 1
    proc = run_checker(*overlap, "--baseline", bl, cwd=tree)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_partial_write_baseline_preserves_out_of_scope_entries(tmp_path):
    # Rewriting the baseline from a partial target list must merge, not
    # drop, grandfathered entries for files outside those targets — a
    # maintenance command that looked successful must not turn the next
    # full-tree gate red.
    pkg = tmp_path / "registrar_tpu"
    pkg.mkdir()
    (pkg / "a.py").write_text("def f(items=[]):\n    return items\n")
    (pkg / "b.py").write_text("def g(x):\n    assert x\n    return x\n")
    bl = str(tmp_path / "bl.json")
    run_checker("registrar_tpu", "--write-baseline", "--baseline", bl, cwd=tmp_path)
    assert len(json.load(open(bl))["findings"]) == 2

    # rewrite from a.py only: b.py's entry must survive ...
    proc = run_checker(
        os.path.join("registrar_tpu", "a.py"),
        "--write-baseline", "--baseline", bl, cwd=tmp_path,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    entries = json.load(open(bl))["findings"]
    assert {e["path"] for e in entries} == {
        "registrar_tpu/a.py", "registrar_tpu/b.py"
    }
    # ... and the full gate stays green
    proc = run_checker("registrar_tpu", "--baseline", bl, cwd=tmp_path)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_malformed_baseline_fails_gate(tmp_path):
    tree = seed_package_tree(tmp_path, "x = 1\n")
    bl = tmp_path / "bl.json"
    bl.write_text("{not json")
    proc = run_checker("registrar_tpu", "--baseline", str(bl), cwd=tree)
    assert proc.returncode == 2
    assert "malformed baseline" in proc.stderr

    # structurally bad entries get the same clean exit, not a traceback
    bl.write_text(json.dumps({"version": 1, "findings": [{"path": "x.py"}]}))
    proc = run_checker("registrar_tpu", "--baseline", str(bl), cwd=tree)
    assert proc.returncode == 2
    assert "malformed baseline" in proc.stderr


def test_engine_findings_cannot_be_grandfathered(tmp_path):
    # --write-baseline on a tree with a syntax error must not produce a
    # baseline that green-lights the unparseable file (no rule analyzes
    # it at all); and a hand-edited baseline smuggling an engine rule
    # in is rejected at load time.
    tree = seed_package_tree(tmp_path, SEEDED_VIOLATIONS["syntax-error"])
    bl = str(tmp_path / "bl.json")
    proc = run_checker("registrar_tpu", "--write-baseline", "--baseline", bl, cwd=tree)
    assert proc.returncode == 1
    assert "cannot be grandfathered" in proc.stderr
    assert json.load(open(bl))["findings"] == []  # excluded from the file

    bl2 = tmp_path / "bl2.json"
    bl2.write_text(json.dumps({
        "version": 1,
        "findings": [{"path": "registrar_tpu/seeded.py",
                      "rule": "syntax-error", "message": "whatever"}],
    }))
    proc = run_checker("registrar_tpu", "--baseline", str(bl2), cwd=tree)
    assert proc.returncode == 2
    assert "grandfathers engine finding" in proc.stderr


def test_stale_check_is_cwd_independent_for_partial_targets(tmp_path):
    # A partial-target run from a subdirectory must not condemn entries
    # for files outside its targets (staleness scopes by target
    # coverage, not by probing the filesystem from whatever cwd).
    pkg = tmp_path / "registrar_tpu"
    sub = pkg / "zk"
    sub.mkdir(parents=True)
    (pkg / "bad.py").write_text("def f(items=[]):\n    return items\n")
    (sub / "mod.py").write_text("x = 1\n")
    bl = str(tmp_path / "bl.json")
    run_checker("registrar_tpu", "--write-baseline", "--baseline", bl, cwd=tmp_path)

    # run from INSIDE the package against a subtree: the bad.py entry
    # is out of scope and must not go stale
    proc = run_checker("zk", "--baseline", bl, cwd=pkg)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# --- output formats ----------------------------------------------------------


def test_json_format(tmp_path):
    tree = seed_package_tree(tmp_path, SEEDED_VIOLATIONS["dropped-task"])
    proc = run_checker(
        "registrar_tpu", "--no-baseline", "--format", "json", cwd=tree
    )
    assert proc.returncode == 1
    report = json.loads(proc.stdout)
    assert report["version"] == 1
    assert report["problem_count"] == 1
    (finding,) = report["problems"]
    assert finding["rule"] == "dropped-task"
    assert finding["path"] == "registrar_tpu/seeded.py"
    assert finding["line"] == 4
    assert "create_task" in finding["message"]


def test_json_output_file(tmp_path):
    tree = seed_package_tree(tmp_path, "x = 1\n")
    out = tmp_path / "report.json"
    proc = run_checker(
        "registrar_tpu", "--no-baseline", "--format", "json",
        "--output", str(out), cwd=tree,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(out.read_text())
    assert report["problem_count"] == 0
    assert report["checked_files"] == 1


# --- the original name rules (regression suite, ported) ----------------------


def test_unused_import_fails_gate(tmp_path):
    msgs = messages("import os\nimport sys\nprint(sys.argv)\n", tmp_path)
    assert msgs == ["unused import 'os'"]


def test_undefined_name_fails_gate(tmp_path):
    msgs = messages("def f():\n    return undefined_thing\n", tmp_path)
    assert msgs == ["undefined name 'undefined_thing'"]


def test_syntax_error_is_reported(tmp_path):
    msgs = messages("def f(:\n", tmp_path)
    assert len(msgs) == 1 and msgs[0].startswith("syntax error")


@pytest.mark.parametrize(
    "source",
    [
        # __all__ strings count as usage (re-export surface).
        "import os\n__all__ = ['os']\n",
        # explicit re-export convention
        "import os as os\n",
        # used only in a type annotation (kept as AST under
        # `from __future__ import annotations` too)
        "from __future__ import annotations\nimport typing\n"
        "def f(x: typing.Any): return x\n",
        # conditional import fallback
        "try:\n    import json\nexcept ImportError:\n    json = None\n"
        "print(json)\n",
    ],
)
def test_import_usage_patterns_pass(source, tmp_path):
    assert messages(source, tmp_path) == []


@pytest.mark.parametrize(
    "source",
    [
        # comprehension target is local to the comprehension
        "xs = [i for i in range(3)]\nprint(xs)\n",
        # walrus binds in the enclosing function scope
        "def f(v):\n    if (n := len(v)) > 1:\n        return n\n",
        # global statement binds at module level
        "def f():\n    global counter\n    counter = 1\n"
        "def g():\n    return counter\n",
        # class attributes are not visible in methods (self.x is fine)
        "class C:\n    x = 1\n    def m(self):\n        return self.x\n",
        # except ... as e binds
        "try:\n    pass\nexcept ValueError as e:\n    print(e)\n",
        # tuple-unpacking for-loop targets bind both names
        "def f(x):\n    for k, v in x.items():\n        yield k, v\n",
        # decorators and defaults
        "import functools\n@functools.wraps(print)\ndef f(a=len('x')):\n"
        "    return a\n",
        # lambda args
        "f = lambda a, *rest, **kw: (a, rest, kw)\nprint(f(1))\n",
        # del unbinds but is a binding occurrence, not a load
        "x = 1\ndel x\n",
        # nested function sees enclosing bindings
        "def outer():\n    y = 2\n    def inner():\n        return y\n"
        "    return inner\n",
    ],
)
def test_scoping_patterns_pass(source, tmp_path):
    assert messages(source, tmp_path) == []


def test_class_scope_invisible_to_methods(tmp_path):
    msgs = messages(
        "class C:\n    x = 1\n    def m(self):\n        return x\n",
        tmp_path,
    )
    assert msgs == ["undefined name 'x'"]


def test_star_import_disables_undefined_check(tmp_path):
    assert messages("from os.path import *\nprint(join('a'))\n", tmp_path) == []


# --- generation 2: whole-program rules ---------------------------------------


def seed_program_tree(tmp_path, files):
    """Materialize a {rel path: source} tree (the multi-file analog of
    seed_package_tree, for the cross-module rules)."""
    for rel, source in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(source))
    return tmp_path


def program_rules_fired(proc):
    """The distinct rule tags a subprocess run printed."""
    import re

    return sorted(set(re.findall(r"\[([a-z-]+)\]", proc.stdout)))


@pytest.mark.parametrize("rule", sorted(PROGRAM_SEEDED_VIOLATIONS))
def test_program_seeded_violation_fails_gate(rule, tmp_path):
    """Mutation-style, like test_seeded_violation_fails_gate: inject the
    cross-module violation and the full gate must fail on that rule —
    and on ONLY that rule (the fixtures are clean otherwise)."""
    tree = seed_program_tree(tmp_path, PROGRAM_SEEDED_VIOLATIONS[rule])
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert program_rules_fired(proc) == [rule]


def test_bench_metric_drift_fires_every_direction(tmp_path):
    # The fixture seeds all three legs: a declared-pinned metric with no
    # history entry, a history pin bench no longer declares, and a
    # PERF.md table citing a name neither surface knows (its token
    # contains the substring "metric" — a header/data-row confusion
    # must not skip it).
    tree = seed_program_tree(
        tmp_path, PROGRAM_SEEDED_VIOLATIONS["bench-metric-drift"]
    )
    proc = run_checker(
        "registrar_tpu", "--no-baseline", "--format", "json", cwd=tree
    )
    assert proc.returncode == 1
    msgs = [p["message"] for p in json.loads(proc.stdout)["problems"]]
    assert any("ghost_metric_ms" in m for m in msgs)  # declared, unpinned
    assert any("orphaned_metric_ms" in m for m in msgs)  # pinned, undeclared
    assert any("phantom_metric_ms" in m for m in msgs)  # doc cites unknown
    assert not any("shared_metric_ms" in m for m in msgs)  # consistent


def test_transitive_blocking_chain_in_json_report(tmp_path):
    tree = seed_program_tree(
        tmp_path, PROGRAM_SEEDED_VIOLATIONS["transitive-blocking-call"]
    )
    proc = run_checker(
        "registrar_tpu", "--no-baseline", "--format", "json", cwd=tree
    )
    assert proc.returncode == 1
    report = json.loads(proc.stdout)
    (finding,) = report["problems"]
    assert finding["rule"] == "transitive-blocking-call"
    # structured evidence: every hop carries symbol/path/line, ending at
    # the blocking primitive
    chain = finding["chain"]
    assert [h["symbol"] for h in chain] == [
        "registrar_tpu.seeded:main",
        "registrar_tpu.util:pause",
        "time.sleep",
    ]
    assert all(
        set(h) == {"symbol", "path", "line"} and h["line"] > 0
        for h in chain
    )
    # the chain also rides in the message (names only), so the text
    # output and the baseline identity pin it too
    assert "registrar_tpu.util:pause -> time.sleep" in finding["message"]


def test_lock_free_mutator_chain_in_json_report(tmp_path):
    tree = seed_program_tree(
        tmp_path, PROGRAM_SEEDED_VIOLATIONS["await-in-lock-free-mutator"]
    )
    proc = run_checker(
        "registrar_tpu", "--no-baseline", "--format", "json", cwd=tree
    )
    assert proc.returncode == 1
    report = json.loads(proc.stdout)
    (finding,) = report["problems"]
    assert finding["rule"] == "await-in-lock-free-mutator"
    chain = finding["chain"]
    assert chain[-1]["symbol"] == "zk.set_data"
    assert chain[0]["symbol"] == "registrar_tpu.agent:repair"


def test_mutator_under_lock_passes(tmp_path):
    tree = seed_program_tree(tmp_path, {
        "registrar_tpu/agent.py": """\
            async def repair(zk, lock):
                async with lock:
                    await zk.set_data("/a", b"x")
            """,
    })
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_mutator_in_helper_only_called_under_lock_passes(tmp_path):
    # The interprocedural leg: the helper's own mutator site is bare,
    # but every resolved caller holds the lock — the greatest-fixpoint
    # "always locked" analysis must keep the gate green.
    tree = seed_program_tree(tmp_path, {
        "registrar_tpu/agent.py": """\
            async def entry(zk, lock):
                async with lock:
                    await _helper(zk)

            async def _helper(zk):
                await zk.set_data("/a", b"x")
            """,
    })
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_op_delete_constructor_is_not_a_mutator(tmp_path):
    # `Op.delete(path)` BUILDS a request object (a class attribute of a
    # model class); only opaque-object receivers (zk, self.zk) count.
    tree = seed_program_tree(tmp_path, {
        "registrar_tpu/ops.py": """\
            class Op:
                @staticmethod
                def delete(path):
                    return ("delete", path)
            """,
        "registrar_tpu/agent.py": """\
            from registrar_tpu.ops import Op

            async def plan(paths):
                return [Op.delete(p) for p in paths]
            """,
    })
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_wait_for_counts_as_listener(tmp_path):
    tree = seed_program_tree(tmp_path, {
        "registrar_tpu/seeded.py": """\
            def fire(ee):
                ee.emit("registered", 1)
            """,
        "registrar_tpu/consumer.py": """\
            async def watch(ee):
                return await ee.wait_for("registered")
            """,
    })
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_dynamic_event_names_are_not_modeled(tmp_path):
    # The client's per-path watch emitter: emit(variable) / on(variable)
    # must neither crash nor count as emits/listens (no guessed names).
    tree = seed_program_tree(tmp_path, {
        "registrar_tpu/seeded.py": """\
            def relay(ee, event, payload):
                ee.on(event, print)
                ee.emit(event, payload)
            """,
    })
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_program_finding_is_suppressible_inline(tmp_path):
    tree = seed_program_tree(tmp_path, {
        "registrar_tpu/seeded.py": """\
            def fire(ee):
                # check: disable=dead-event-name -- embedders subscribe to this
                ee.emit("registered", 1)
            """,
    })
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_program_finding_unused_suppression_reported(tmp_path):
    tree = seed_program_tree(tmp_path, {
        "registrar_tpu/seeded.py": """\
            def fire(ee):
                # check: disable=dead-event-name -- stale excuse
                ee.on("registered", print)
                ee.emit("registered", 1)
            """,
    })
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree)
    assert proc.returncode == 1
    assert program_rules_fired(proc) == ["unused-suppression"]


def test_import_cycle_degrades_gracefully(tmp_path):
    # a <-> b: the model never executes imports, so a cycle must neither
    # crash nor lose resolution — the violation inside it still fires.
    tree = seed_program_tree(tmp_path, {
        "registrar_tpu/a.py": """\
            from registrar_tpu import b

            async def touch():
                b.helper()
            """,
        "registrar_tpu/b.py": """\
            from registrar_tpu import a

            async def helper():
                return a
            """,
    })
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert program_rules_fired(proc) == ["cross-module-unawaited"]


def test_star_import_degrades_module_to_file_local(tmp_path):
    # A `from x import *` can shadow ANY name at runtime; the program
    # model must stop resolving names in that module (conservative
    # silence) instead of false-positiving on the explicit import.
    tree = seed_program_tree(tmp_path, {
        "registrar_tpu/util.py": """\
            import time

            def pause():
                time.sleep(1)
            """,
        "registrar_tpu/other.py": """\
            VALUE = 1
            """,
        "registrar_tpu/seeded.py": """\
            from registrar_tpu.util import pause
            from registrar_tpu.other import *

            async def main():
                pause()
            """,
    })
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_dynamic_import_degrades_module_to_file_local(tmp_path):
    tree = seed_program_tree(tmp_path, {
        "registrar_tpu/util.py": """\
            import time

            def pause():
                time.sleep(1)
            """,
        "registrar_tpu/seeded.py": """\
            import importlib

            from registrar_tpu.util import pause

            plugin = importlib.import_module("registrar_tpu.util")

            async def main():
                pause()
            """,
    })
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_rebound_name_is_ambiguous_and_silent(tmp_path):
    # An imported async def later rebound at module level: the bare call
    # could hit either binding — a build gate must not guess.
    tree = seed_program_tree(tmp_path, {
        "registrar_tpu/util.py": """\
            import asyncio

            async def notify():
                await asyncio.sleep(0)
            """,
        "registrar_tpu/seeded.py": """\
            from registrar_tpu.util import notify

            def quiet():
                return None

            notify = quiet

            async def main():
                notify()
            """,
    })
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_secret_flow_through_local_assignment(tmp_path):
    tree = seed_program_tree(tmp_path, {
        "registrar_tpu/seeded.py": """\
            import logging

            log = logging.getLogger("registrar")

            def announce(state):
                secret = state.passwd
                shown = secret
                log.info("resuming with %r", shown)
            """,
    })
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree)
    assert proc.returncode == 1
    assert program_rules_fired(proc) == ["secret-flow-to-log"]


def test_secret_sibling_fields_log_fine(tmp_path):
    # session_id is logged all over the tree by design — only the
    # passwd is the secret.
    tree = seed_program_tree(tmp_path, {
        "registrar_tpu/seeded.py": """\
            import logging

            log = logging.getLogger("registrar")

            def announce(state):
                state.passwd = b"x" * 16
                log.info("session 0x%x", state.session_id)
            """,
    })
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_config_key_drift_reports_each_direction(tmp_path):
    tree = seed_program_tree(
        tmp_path, PROGRAM_SEEDED_VIOLATIONS["config-key-drift"]
    )
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree)
    assert proc.returncode == 1
    out = proc.stdout
    # each drift direction is its own finding, anchored at its source
    assert "'ghostKey' is read by the accessors but never documented" in out
    assert "'ghostKey' is read by the accessors but not exercised" in out
    assert "'timeout' is documented but no accessor reads it" in out
    assert "'timeout' is documented but missing from etc/" in out
    assert "'exampleOnly' is present in the example config but no accessor" in out
    assert "'exampleOnly' is present in the example config but never documented" in out


def test_subtree_run_skips_program_rules(tmp_path):
    # `check.py registrar_tpu/zk` (the documented subtree convenience)
    # must not judge cross-module contracts against an artificially
    # small program — the real tree's zk/ subtree emits events whose
    # listeners live elsewhere, and that run must stay green.
    proc = run_checker(os.path.join("registrar_tpu", "zk"), "--no-baseline")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # ... while a tree-rooted run still arms them (the fixture suite
    # above relies on it); single-file runs skip them too
    tree = seed_program_tree(
        tmp_path, PROGRAM_SEEDED_VIOLATIONS["dead-event-name"]
    )
    proc = run_checker(
        os.path.join("registrar_tpu", "seeded.py"), "--no-baseline",
        cwd=tree,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_checklib_modules_resolve_in_import_graph():
    # tools/ sits on sys.path for the checker, so tools/checklib/*.py
    # import as checklib.* — the model must name them that way or the
    # --changed-only reverse-dependency closure silently loses every
    # consumer of a checklib helper.
    from checklib.engine import _parse_file
    from checklib.program import ProgramModel, module_name_for

    assert module_name_for("tools/checklib/program.py") == "checklib.program"
    contexts = []
    for rel in (
        "tools/checklib/program.py",
        "tools/checklib/callgraph.py",
        "tools/checklib/engine.py",
    ):
        ctx, _ = _parse_file(os.path.join(REPO, rel), rel)
        contexts.append(ctx)
    model = ProgramModel(contexts)
    closure = model.reverse_import_closure({"tools/checklib/program.py"})
    assert "tools/checklib/callgraph.py" in closure  # imports program
    assert "tools/checklib/engine.py" in closure  # imports program


def test_secret_taint_not_inherited_by_shadowing_param(tmp_path):
    # A nested function whose PARAMETER shares a tainted outer name is
    # not handling the secret — the closure-taint inheritance must drop
    # shadowed names (zero-false-positive contract).
    tree = seed_program_tree(tmp_path, {
        "registrar_tpu/seeded.py": """\
            import logging

            log = logging.getLogger("registrar")

            def outer(state):
                data = state.passwd

                def fmt(data):
                    log.info("payload %r", data)

                return fmt, data
            """,
    })
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_config_key_drift_silent_without_accessor_module(tmp_path):
    # Fixture trees for the OTHER rules carry no config.py: the drift
    # rule must not condemn their (absent) docs.
    tree = seed_program_tree(tmp_path, {
        "registrar_tpu/seeded.py": "x = 1\n",
    })
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# --- generation 3: exception-flow rules (ISSUE 7) ----------------------------


def _flow_for_tree(tmp_path, files):
    """ProgramModel + ExceptionFlow over a materialized scratch tree
    (the direct-API harness for the escape-set unit tests)."""
    from checklib.engine import _parse_file
    from checklib.exceptions import flow_for
    from checklib.program import ProgramModel

    seed_program_tree(tmp_path, files)
    contexts = []
    for rel in sorted(files):
        if not rel.endswith(".py"):
            continue
        ctx, _ = _parse_file(str(tmp_path / rel), rel)
        assert ctx is not None, rel
        contexts.append(ctx)
    model = ProgramModel(contexts)
    return model, flow_for(model)


def _escape_names(model, flow, ref):
    """Bare class names escaping the function with qualref ``ref``."""
    for f in model.functions():
        if f.ref == ref:
            return {t.rsplit(":", 1)[-1] for t in flow.escapes(f)}
    raise AssertionError(f"no function {ref}")


def test_escape_tuple_handler_catches_both(tmp_path):
    model, flow = _flow_for_tree(tmp_path, {
        "registrar_tpu/seeded.py": """\
            class AErr(Exception):
                pass


            class BErr(Exception):
                pass


            def both(flag):
                try:
                    if flag:
                        raise AErr()
                    raise BErr()
                except (AErr, BErr):
                    return None


            def narrow(flag):
                try:
                    if flag:
                        raise AErr()
                    raise BErr()
                except (AErr,):
                    return None
            """,
    })
    assert _escape_names(model, flow, "registrar_tpu.seeded:both") == set()
    assert _escape_names(model, flow, "registrar_tpu.seeded:narrow") == {
        "BErr"
    }


def test_escape_bare_and_named_reraise(tmp_path):
    model, flow = _flow_for_tree(tmp_path, {
        "registrar_tpu/seeded.py": """\
            class AErr(Exception):
                pass


            def bare():
                try:
                    raise AErr()
                except AErr:
                    raise


            def named():
                try:
                    raise AErr()
                except AErr as e:
                    raise e


            def swallowed():
                try:
                    raise AErr()
                except AErr:
                    return None
            """,
    })
    assert _escape_names(model, flow, "registrar_tpu.seeded:bare") == {"AErr"}
    assert _escape_names(model, flow, "registrar_tpu.seeded:named") == {
        "AErr"
    }
    assert (
        _escape_names(model, flow, "registrar_tpu.seeded:swallowed") == set()
    )


def test_escape_hierarchy_across_modules(tmp_path):
    # `except Base` must catch a Sub raised two modules away, with the
    # base resolved through the cross-module symbol table.
    model, flow = _flow_for_tree(tmp_path, {
        "registrar_tpu/errs.py": """\
            class BaseErr(Exception):
                pass


            class SubErr(BaseErr):
                pass
            """,
        "registrar_tpu/seeded.py": """\
            from registrar_tpu.errs import BaseErr, SubErr


            def boom():
                raise SubErr()


            def caught():
                try:
                    boom()
                except BaseErr:
                    return None


            def wrong_way():
                try:
                    raise BaseErr()
                except SubErr:
                    return None
            """,
    })
    assert _escape_names(model, flow, "registrar_tpu.seeded:boom") == {
        "SubErr"
    }
    assert _escape_names(model, flow, "registrar_tpu.seeded:caught") == set()
    # a SubErr clause does NOT catch the base class
    assert _escape_names(model, flow, "registrar_tpu.seeded:wrong_way") == {
        "BaseErr"
    }


def test_escape_unresolvable_edges_widen_conservatively(tmp_path):
    # An opaque call widens to the UNKNOWN marker (never a named claim);
    # an unresolvable HANDLER clause is assumed to catch everything —
    # both are the fewer-findings direction.
    from checklib.exceptions import UNKNOWN

    model, flow = _flow_for_tree(tmp_path, {
        "registrar_tpu/seeded.py": """\
            class AErr(Exception):
                pass


            def opaque(helper):
                helper()


            def shielded(ns):
                try:
                    raise AErr()
                except ns.Error:
                    return None
            """,
    })
    for f in model.functions():
        if f.ref == "registrar_tpu.seeded:opaque":
            assert flow.escapes(f) == frozenset({UNKNOWN})
    assert (
        _escape_names(model, flow, "registrar_tpu.seeded:shielded") == set()
    )


def test_escape_propagates_through_import_cycle(tmp_path):
    model, flow = _flow_for_tree(tmp_path, {
        "registrar_tpu/a.py": """\
            from registrar_tpu import b


            class CycleErr(Exception):
                pass


            def boom():
                raise CycleErr()
            """,
        "registrar_tpu/b.py": """\
            from registrar_tpu import a


            def relay():
                return a.boom()
            """,
    })
    assert "CycleErr" in _escape_names(model, flow, "registrar_tpu.b:relay")


def test_escape_excludes_cancellation_signals(tmp_path):
    model, flow = _flow_for_tree(tmp_path, {
        "registrar_tpu/seeded.py": """\
            import asyncio


            async def quit_loop():
                raise asyncio.CancelledError()
            """,
    })
    assert (
        _escape_names(model, flow, "registrar_tpu.seeded:quit_loop") == set()
    )


def test_unawaited_async_call_does_not_propagate_escapes(tmp_path):
    # Calling an async def without awaiting builds a coroutine object:
    # nothing raises HERE (the blackhole rule reasons about where the
    # task's exception goes instead).
    model, flow = _flow_for_tree(tmp_path, {
        "registrar_tpu/seeded.py": """\
            class AErr(Exception):
                pass


            async def boom():
                raise AErr()


            async def spawns():
                coro = boom()
                return coro


            async def awaits():
                await boom()
            """,
    })
    assert (
        _escape_names(model, flow, "registrar_tpu.seeded:spawns") == set()
    )
    assert _escape_names(model, flow, "registrar_tpu.seeded:awaits") == {
        "AErr"
    }


def test_escape_finally_and_orelse_propagate(tmp_path):
    model, flow = _flow_for_tree(tmp_path, {
        "registrar_tpu/seeded.py": """\
            class AErr(Exception):
                pass


            class BErr(Exception):
                pass


            def f(flag):
                try:
                    pass
                except ValueError:
                    return None
                else:
                    raise AErr()
                finally:
                    if flag:
                        raise BErr()
            """,
    })
    assert _escape_names(model, flow, "registrar_tpu.seeded:f") == {
        "AErr", "BErr",
    }


def test_retry_contract_chain_in_json_report(tmp_path):
    tree = seed_program_tree(
        tmp_path, PROGRAM_SEEDED_VIOLATIONS["retry-contract-drift"]
    )
    proc = run_checker(
        "registrar_tpu", "--no-baseline", "--format", "json", cwd=tree
    )
    assert proc.returncode == 1
    report = json.loads(proc.stdout)
    (finding,) = report["problems"]
    assert finding["rule"] == "retry-contract-drift"
    chain = finding["chain"]
    assert chain[0]["symbol"] == "registrar_tpu.seeded:main"
    assert chain[-1]["symbol"] == "raise QuotaError"
    assert all(h["line"] > 0 for h in chain)


def test_retry_contract_classified_subclass_passes(tmp_path):
    # A class is_transient's body DOES name (here: any ConnectionError
    # subclass) is classified — no drift.
    tree = seed_program_tree(tmp_path, {
        "registrar_tpu/retry.py": PROGRAM_SEEDED_VIOLATIONS[
            "retry-contract-drift"
        ]["registrar_tpu/retry.py"],
        "registrar_tpu/seeded.py": """\
            from registrar_tpu.retry import call_with_backoff, is_transient


            class FlakyWire(ConnectionError):
                pass


            async def push(payload):
                if not payload:
                    raise FlakyWire()
                return payload


            async def main(payload):
                return await call_with_backoff(
                    lambda: push(payload), retryable=is_transient
                )
            """,
    })
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_retry_boundary_without_is_transient_is_silent(tmp_path):
    # A custom retryable predicate makes no is_transient promise — the
    # rule must not hold the boundary to a contract it never adopted.
    tree = seed_program_tree(tmp_path, {
        "registrar_tpu/retry.py": PROGRAM_SEEDED_VIOLATIONS[
            "retry-contract-drift"
        ]["registrar_tpu/retry.py"],
        "registrar_tpu/seeded.py": """\
            from registrar_tpu.retry import call_with_backoff


            class QuotaError(Exception):
                pass


            async def push(payload):
                if not payload:
                    raise QuotaError()
                return payload


            async def main(payload):
                return await call_with_backoff(
                    lambda: push(payload),
                    retryable=lambda err: True,
                )
            """,
    })
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_blackhole_awaited_handle_passes(tmp_path):
    # The spawned task's handle IS awaited somewhere in the module: the
    # exception has a consumer; no blackhole.
    tree = seed_program_tree(tmp_path, {
        "registrar_tpu/seeded.py": """\
            import asyncio


            class DropError(Exception):
                pass


            async def pump():
                raise DropError("queue gone")


            def start(tasks):
                t = asyncio.create_task(pump())
                tasks.add(t)
                t.add_done_callback(tasks.discard)
                return t


            async def stop(t):
                await t
            """,
    })
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_blackhole_asyncio_run_is_a_consumer(tmp_path):
    # asyncio.run() re-raises the coroutine's exception in its sync
    # caller — handing a raising coroutine to it is not a blackhole.
    tree = seed_program_tree(tmp_path, {
        "registrar_tpu/seeded.py": """\
            import asyncio


            class DropError(Exception):
                pass


            async def pump():
                raise DropError("queue gone")


            def main():
                asyncio.run(pump())
            """,
    })
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_blackhole_quiet_task_passes(tmp_path):
    # A spawned coroutine that provably raises nothing named is fine —
    # the rule needs a proven escape, not just a fire-and-forget shape.
    tree = seed_program_tree(tmp_path, {
        "registrar_tpu/seeded.py": """\
            import asyncio


            async def pump():
                try:
                    await asyncio.sleep(0)
                except Exception:
                    return None


            def start(tasks):
                t = asyncio.create_task(pump())
                tasks.add(t)
                t.add_done_callback(tasks.discard)
            """,
    })
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_broad_handler_catches_unknown_hierarchy_ext_class(tmp_path):
    # `except Exception` must catch a named EXTERNAL class whose
    # hierarchy the model cannot see (zlib.error): the only modeled
    # BaseException-not-Exception descendants are the excluded signals,
    # so letting it "escape" a broad handler would be a false positive.
    model, flow = _flow_for_tree(tmp_path, {
        "registrar_tpu/seeded.py": """\
            import zlib


            def unpack(blob):
                try:
                    raise zlib.error("boom")
                except Exception:
                    return None
            """,
    })
    assert _escape_names(model, flow, "registrar_tpu.seeded:unpack") == set()


def test_blackhole_batched_gather_passes(tmp_path):
    # A coroutine appended to a batch and gathered later is consumed —
    # only a real spawner (create_task/ensure_future/spawn_owned) makes
    # a fire-and-forget task root.
    tree = seed_program_tree(tmp_path, {
        "registrar_tpu/seeded.py": """\
            import asyncio


            class DropError(Exception):
                pass


            async def refresh():
                raise DropError()


            async def drive(pending):
                pending.append(refresh())
                await asyncio.gather(*pending)
            """,
    })
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_overbroad_without_upstream_handler_passes(tmp_path):
    # Swallowing a contract class is only condemned when a caller
    # handles that class explicitly (evidence the design wants it).
    files = dict(PROGRAM_SEEDED_VIOLATIONS["overbroad-handler"])
    files["registrar_tpu/seeded.py"] = """\
        import logging

        from registrar_tpu import store

        log = logging.getLogger("seeded")


        async def tick(tree):
            try:
                return await store.refresh(tree)
            except Exception:
                log.info("refresh failed")
                return None


        async def drive(tree):
            return await tick(tree)
        """
    tree = seed_program_tree(tmp_path, files)
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_overbroad_handler_requires_enclosing_try(tmp_path):
    # The caller's narrow handler must ENCLOSE the call into the
    # flagged function: a handler around some unrelated statement could
    # never receive the exception, so it is not evidence.
    files = dict(PROGRAM_SEEDED_VIOLATIONS["overbroad-handler"])
    files["registrar_tpu/seeded.py"] = """\
        import logging

        from registrar_tpu import store
        from registrar_tpu.errs import SessionExpiredError

        log = logging.getLogger("seeded")


        async def tick(tree):
            try:
                return await store.refresh(tree)
            except Exception:
                log.info("refresh failed")
                return None


        async def drive(tree):
            try:
                log.info("starting")
            except SessionExpiredError:
                return None
            return await tick(tree)
        """
    tree = seed_program_tree(tmp_path, files)
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_escape_chain_skips_caught_raise_sites(tmp_path):
    # Witnesses travel with their tokens through handler filtering: a
    # raise that is locally caught must never anchor the evidence chain
    # for a token that escaped some other way.
    model, flow = _flow_for_tree(tmp_path, {
        "registrar_tpu/errs.py": """\
            class WireError(Exception):
                pass


            def probe():
                raise WireError()
            """,
        "registrar_tpu/seeded.py": """\
            from registrar_tpu.errs import WireError, probe


            def f():
                try:
                    raise WireError()
                except WireError:
                    pass
                probe()
            """,
    })
    for func in model.functions():
        if func.ref == "registrar_tpu.seeded:f":
            token = next(iter(flow.named_escapes(func)))
            chain = flow.escape_chain(func, token)
            # the witness is the probe() call (line 9), not the caught
            # raise (line 6)
            assert chain[0][2] == 9, chain
            assert chain[-1][0] == "raise WireError"
            break
    else:
        raise AssertionError("f not found")


def test_overbroad_narrow_then_broad_passes(tmp_path):
    # The canonical defensive pattern: a narrow clause for the contract
    # class AHEAD of the broad catch-all.  Clause order means the broad
    # handler can never receive the class — not a swallow.
    files = dict(PROGRAM_SEEDED_VIOLATIONS["overbroad-handler"])
    files["registrar_tpu/seeded.py"] = """\
        import logging

        from registrar_tpu import store
        from registrar_tpu.errs import SessionExpiredError

        log = logging.getLogger("seeded")


        async def recover():
            log.info("recovering")


        async def tick(tree):
            try:
                return await store.refresh(tree)
            except SessionExpiredError:
                await recover()
                return None
            except Exception:
                log.info("refresh failed")
                return None


        async def drive(tree):
            try:
                return await tick(tree)
            except SessionExpiredError:
                return None
        """
    tree = seed_program_tree(tmp_path, files)
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_blackhole_annassign_stored_handle_passes(tmp_path):
    # A handle stored through an ANNOTATED assignment and awaited in
    # another method is consumed — AnnAssign targets must enter the
    # consumed-handle check like plain Assign targets.
    tree = seed_program_tree(tmp_path, {
        "registrar_tpu/seeded.py": """\
            import asyncio


            class DropError(Exception):
                pass


            async def pump():
                raise DropError("queue gone")


            class Owner:
                def start(self):
                    self._task: asyncio.Task = asyncio.create_task(pump())

                async def stop(self):
                    await self._task
            """,
    })
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_retry_contract_keyword_thunk_fires(tmp_path):
    # Refactoring a boundary to `fn=...` keyword style must not drop it
    # from the contract check.
    files = dict(PROGRAM_SEEDED_VIOLATIONS["retry-contract-drift"])
    files["registrar_tpu/seeded.py"] = textwrap.dedent(
        files["registrar_tpu/seeded.py"]
    ).replace(
        "lambda: push(payload), retryable=is_transient",
        "fn=lambda: push(payload), retryable=is_transient",
    )
    tree = seed_program_tree(tmp_path, files)
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert program_rules_fired(proc) == ["retry-contract-drift"]


def test_retry_contract_chain_names_the_real_origin(tmp_path):
    # A lambda combining several calls must attribute the token to the
    # callee it actually escaped from, with the chain ending at the
    # raise — never at an innocent function.
    files = dict(PROGRAM_SEEDED_VIOLATIONS["retry-contract-drift"])
    files["registrar_tpu/seeded.py"] = """\
        from registrar_tpu.retry import call_with_backoff, is_transient


        class QuotaError(Exception):
            pass


        def prep(payload):
            return payload


        async def push(payload):
            if not payload:
                raise QuotaError()
            return payload


        async def main(payload):
            return await call_with_backoff(
                lambda: push(prep(payload)), retryable=is_transient
            )
        """
    tree = seed_program_tree(tmp_path, files)
    proc = run_checker(
        "registrar_tpu", "--no-baseline", "--format", "json", cwd=tree
    )
    assert proc.returncode == 1
    report = json.loads(proc.stdout)
    (finding,) = report["problems"]
    chain = finding["chain"]
    assert chain[1]["symbol"] == "registrar_tpu.seeded:push"
    assert chain[-1]["symbol"] == "raise QuotaError"


def test_overbroad_reraising_handler_passes(tmp_path):
    # A broad handler that may re-throw is not a swallow.
    files = dict(PROGRAM_SEEDED_VIOLATIONS["overbroad-handler"])
    files["registrar_tpu/seeded.py"] = """\
        import logging

        from registrar_tpu import store
        from registrar_tpu.errs import SessionExpiredError

        log = logging.getLogger("seeded")


        async def tick(tree):
            try:
                return await store.refresh(tree)
            except Exception:
                log.info("refresh failed")
                raise


        async def drive(tree):
            try:
                return await tick(tree)
            except SessionExpiredError:
                return None
        """
    tree = seed_program_tree(tmp_path, files)
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_fault_matrix_live_class_passes(tmp_path):
    # Docs naming a class the program really raises (or constructs) is
    # in sync — even when every raise of it is locally handled.
    tree = seed_program_tree(tmp_path, {
        "registrar_tpu/seeded.py": """\
            class QuotaError(Exception):
                pass


            def check(n):
                try:
                    if n > 5:
                        raise QuotaError()
                except QuotaError:
                    return None
            """,
        "docs/FAULTS.md": """\
            # Faults

            Quota exhaustion raises `QuotaError`.
            """,
    })
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_fault_matrix_builtin_mentions_pass(tmp_path):
    # A runbook may name any builtin the analysis itself knows
    # (BrokenPipeError, EOFError, ...) without being condemned as
    # naming a nonexistent class.
    tree = seed_program_tree(tmp_path, {
        "registrar_tpu/seeded.py": "x = 1\n",
        "docs/FAULTS.md": """\
            # Faults

            A half-closed socket surfaces as `BrokenPipeError` or
            `ConnectionResetError`; an aborted handshake as `EOFError`.
            """,
    })
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_metric_wildcard_and_known_names_pass(tmp_path):
    tree = seed_program_tree(tmp_path, {
        "registrar_tpu/metrics.py": PROGRAM_SEEDED_VIOLATIONS[
            "metric-name-drift"
        ]["registrar_tpu/metrics.py"],
        "docs/OPERATIONS.md": """\
            # Operating

            Alert on `registrar_beats_total`; the whole family is
            `registrar_*` (grep registrar_ for everything).
            """,
    })
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_new_rule_inline_suppressions(tmp_path):
    # Each code-anchored generation-3 finding must be suppressible at
    # its anchor line like any other finding (doc-anchored ones ride
    # the baseline instead — no inline directives in markdown).
    files = dict(PROGRAM_SEEDED_VIOLATIONS["retry-contract-drift"])
    files["registrar_tpu/seeded.py"] = textwrap.dedent(
        files["registrar_tpu/seeded.py"]
    ).replace(
        "    return await call_with_backoff(",
        "    # check: disable=retry-contract-drift -- fixture accepts the "
        "silent non-retry\n    return await call_with_backoff(",
    )
    tree = seed_program_tree(tmp_path, files)
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree)
    assert proc.returncode == 0, proc.stdout + proc.stderr

    files = dict(PROGRAM_SEEDED_VIOLATIONS["task-exception-blackhole"])
    files["registrar_tpu/seeded.py"] = textwrap.dedent(
        files["registrar_tpu/seeded.py"]
    ).replace(
        "    t = asyncio.create_task(pump())",
        "    # check: disable=task-exception-blackhole -- fixture drops it\n"
        "    t = asyncio.create_task(pump())",
    )
    tree2 = tmp_path / "blackhole"
    tree2.mkdir()
    seed_program_tree(tree2, files)
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree2)
    assert proc.returncode == 0, proc.stdout + proc.stderr

    files = dict(PROGRAM_SEEDED_VIOLATIONS["overbroad-handler"])
    files["registrar_tpu/seeded.py"] = textwrap.dedent(
        files["registrar_tpu/seeded.py"]
    ).replace(
        "    except Exception:",
        "    # check: disable=overbroad-handler -- fixture flattens all "
        "failures\n    except Exception:",
    )
    tree3 = tmp_path / "overbroad"
    tree3.mkdir()
    seed_program_tree(tree3, files)
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree3)
    assert proc.returncode == 0, proc.stdout + proc.stderr


@pytest.mark.parametrize(
    "rule",
    [
        "retry-contract-drift",
        "task-exception-blackhole",
        "overbroad-handler",
        "fault-matrix-drift",
        "metric-name-drift",
    ],
)
def test_new_rule_baseline_round_trip(rule, tmp_path):
    tree = seed_program_tree(tmp_path, PROGRAM_SEEDED_VIOLATIONS[rule])
    bl = str(tmp_path / "bl.json")
    proc = run_checker(
        "registrar_tpu", "--write-baseline", "--baseline", bl, cwd=tree
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert json.load(open(bl))["findings"], "nothing grandfathered?"
    proc = run_checker("registrar_tpu", "--baseline", bl, cwd=tree)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# --- generation 4: locks, lifecycles, wire contracts (ISSUE 15) --------------


def test_lock_order_cycle_chains_in_json_and_sarif(tmp_path):
    tree = seed_program_tree(
        tmp_path, PROGRAM_SEEDED_VIOLATIONS["lock-order-cycle"]
    )
    proc = run_checker(
        "registrar_tpu", "--no-baseline", "--format", "json", cwd=tree
    )
    assert proc.returncode == 1
    (finding,) = json.loads(proc.stdout)["problems"]
    assert finding["rule"] == "lock-order-cycle"
    # BOTH acquisition orders ride along as one concatenated evidence
    # chain: the interprocedural repair->_flush side and the lexical
    # snapshot inversion
    symbols = [h["symbol"] for h in finding["chain"]]
    assert "async with repair_lock" in symbols
    assert "async with state_lock" in symbols
    assert "registrar_tpu.agent:_flush" in symbols
    assert "registrar_tpu.agent:snapshot" in symbols
    assert all(
        set(h) == {"symbol", "path", "line"}
        and h["path"] == "registrar_tpu/agent.py"
        and h["line"] > 0
        for h in finding["chain"]
    )
    # one names-only chain per side of the inversion in the message
    assert " vs " in finding["message"]
    assert "repair_lock -> state_lock -> repair_lock" in finding["message"]
    # the same hops, in order, in the SARIF codeFlow
    proc = run_checker(
        "registrar_tpu", "--no-baseline", "--format", "sarif", cwd=tree
    )
    assert proc.returncode == 1
    (result,) = json.loads(proc.stdout)["runs"][0]["results"]
    assert result["ruleId"] == "lock-order-cycle"
    (flow,) = result["codeFlows"]
    (thread,) = flow["threadFlows"]
    assert [
        h["location"]["message"]["text"] for h in thread["locations"]
    ] == symbols


def test_lock_diamond_consistent_order_has_no_cycle(tmp_path):
    # Two paths (one lexical, one through a helper) both take
    # alpha -> beta: an edge, but no inversion — conservative silence.
    from checklib.locks import lockgraph_for

    model, _ = _flow_for_tree(tmp_path, {
        "registrar_tpu/agent.py": """\
            import asyncio

            alpha_lock = asyncio.Lock()
            beta_lock = asyncio.Lock()


            async def left():
                async with alpha_lock:
                    await _inner()


            async def right():
                async with alpha_lock:
                    async with beta_lock:
                        pass


            async def _inner():
                async with beta_lock:
                    pass
            """,
    })
    lg = lockgraph_for(model)
    assert (
        "registrar_tpu.agent:alpha_lock",
        "registrar_tpu.agent:beta_lock",
    ) in lg.edges
    assert lg.cycles() == []


def test_lifecycle_ownership_transfer_is_exempt(tmp_path):
    # `return proxy` hands the handle to the caller: the callee is no
    # longer responsible for releasing it.
    from checklib.lifecycle import lifecycle_for

    model, _ = _flow_for_tree(tmp_path, {
        "registrar_tpu/netem.py": """\
            class ChaosProxy:
                async def start(self):
                    return self

                async def stop(self):
                    pass


            async def build(addr):
                proxy = await ChaosProxy(addr).start()
                return proxy
            """,
    })
    assert lifecycle_for(model).findings["leaked-resource"] == []


def test_lifecycle_cm_bound_resource_is_exempt(tmp_path):
    # `async with ChaosProxy(...)` — the context manager owns release.
    from checklib.lifecycle import lifecycle_for

    model, _ = _flow_for_tree(tmp_path, {
        "registrar_tpu/netem.py": """\
            class ChaosProxy:
                async def stop(self):
                    pass


            async def probe(addr):
                async with ChaosProxy(addr) as proxy:
                    return addr
            """,
    })
    assert lifecycle_for(model).findings["leaked-resource"] == []


def test_lifecycle_escape_path_leak_fires(tmp_path):
    # A release EXISTS but sits on the straight-line path, not in a
    # finally: the named escape between acquire and release leaks the
    # handle, and the evidence chain walks acquire -> raise origin.
    from checklib.lifecycle import lifecycle_for

    model, _ = _flow_for_tree(tmp_path, {
        "registrar_tpu/netem.py": """\
            class ChaosProxy:
                async def start(self):
                    return self

                async def stop(self):
                    pass


            class RegistrarError(Exception):
                pass


            def risky():
                raise RegistrarError("boom")


            async def probe(addr):
                proxy = await ChaosProxy(addr).start()
                risky()
                await proxy.stop()
            """,
    })
    (finding,) = lifecycle_for(model).findings["leaked-resource"]
    assert finding.path == "registrar_tpu/netem.py"
    assert "RegistrarError" in finding.message
    assert "no release sits in a finally" in finding.message
    symbols = [hop["symbol"] for hop in finding.chain]
    assert symbols[0] == "proxy = ChaosProxy(...)"


# --- generation 5: taint flow + await atomicity (ISSUE 16) -------------------


def test_unbounded_allocation_chain_in_json_report(tmp_path):
    tree = seed_program_tree(
        tmp_path, PROGRAM_SEEDED_VIOLATIONS["unbounded-peer-allocation"]
    )
    proc = run_checker(
        "registrar_tpu", "--no-baseline", "--format", "json", cwd=tree
    )
    assert proc.returncode == 1
    (finding,) = json.loads(proc.stdout)["problems"]
    assert finding["rule"] == "unbounded-peer-allocation"
    # the witness chain walks peer read -> sized allocation, hop for hop
    assert [h["symbol"] for h in finding["chain"]] == [
        "unpack (peer read)",
        "tainted * sequence",
    ]
    assert all(
        set(h) == {"symbol", "path", "line"}
        and h["path"] == "registrar_tpu/shard.py"
        and h["line"] > 0
        for h in finding["chain"]
    )
    # the names-only chain rides in the message (baseline identity)
    assert "chain:" in finding["message"]
    assert "unpack (peer read)" in finding["message"]


def test_count_loop_chain_crosses_modules(tmp_path):
    # The interprocedural leg: the peer read lives in the jute reader,
    # the unchecked range() two modules away — the chain must carry the
    # cross-module hop.
    tree = seed_program_tree(
        tmp_path, PROGRAM_SEEDED_VIOLATIONS["unvalidated-count-loop"]
    )
    proc = run_checker(
        "registrar_tpu", "--no-baseline", "--format", "json", cwd=tree
    )
    assert proc.returncode == 1
    (finding,) = json.loads(proc.stdout)["problems"]
    assert finding["rule"] == "unvalidated-count-loop"
    assert finding["path"] == "registrar_tpu/seeded.py"
    chain = finding["chain"]
    assert [h["symbol"] for h in chain] == [
        "unpack_from (peer read)",
        "registrar_tpu.seeded:load_items",
        "range(tainted)",
    ]
    assert chain[0]["path"] == "registrar_tpu/zk/jute.py"
    assert chain[-1]["path"] == "registrar_tpu/seeded.py"


def test_peer_read_size_chain_in_json_and_sarif(tmp_path):
    tree = seed_program_tree(
        tmp_path, PROGRAM_SEEDED_VIOLATIONS["unchecked-peer-read-size"]
    )
    proc = run_checker(
        "registrar_tpu", "--no-baseline", "--format", "json", cwd=tree
    )
    assert proc.returncode == 1
    (finding,) = json.loads(proc.stdout)["problems"]
    assert finding["rule"] == "unchecked-peer-read-size"
    symbols = [h["symbol"] for h in finding["chain"]]
    assert symbols == ["unpack (peer read)", "readexactly(tainted)"]
    # the same hops, in order, in the SARIF codeFlow
    proc = run_checker(
        "registrar_tpu", "--no-baseline", "--format", "sarif", cwd=tree
    )
    assert proc.returncode == 1
    (result,) = json.loads(proc.stdout)["runs"][0]["results"]
    assert result["ruleId"] == "unchecked-peer-read-size"
    (flow,) = result["codeFlows"]
    (thread,) = flow["threadFlows"]
    assert [
        h["location"]["message"]["text"] for h in thread["locations"]
    ] == symbols


def test_stale_read_chain_in_json_report(tmp_path):
    tree = seed_program_tree(
        tmp_path, PROGRAM_SEEDED_VIOLATIONS["stale-read-across-await"]
    )
    proc = run_checker(
        "registrar_tpu", "--no-baseline", "--format", "json", cwd=tree
    )
    assert proc.returncode == 1
    (finding,) = json.loads(proc.stdout)["problems"]
    assert finding["rule"] == "stale-read-across-await"
    # anchored at the stale read; three hops read -> await -> write
    assert [h["symbol"] for h in finding["chain"]] == [
        "read ee.count",
        "await",
        "write ee.count",
    ]
    assert finding["line"] == finding["chain"][0]["line"]


def test_taint_boundary_drift_fires_both_directions(tmp_path):
    # The fixture seeds both legs at once: a stale source row (jute
    # read_int with no such call site) and a live peer read (shard
    # struct.unpack) with no row.  The sink vocabulary is complete, so
    # only the source directions fire.
    tree = seed_program_tree(
        tmp_path, PROGRAM_SEEDED_VIOLATIONS["taint-boundary-drift"]
    )
    proc = run_checker(
        "registrar_tpu", "--no-baseline", "--format", "json", cwd=tree
    )
    assert proc.returncode == 1
    problems = json.loads(proc.stdout)["problems"]
    assert {p["rule"] for p in problems} == {"taint-boundary-drift"}
    msgs = sorted(p["message"] for p in problems)
    assert any(
        "declares source 'read_int'" in m and "stale row" in m
        for m in msgs
    )
    assert any(
        "peer-read call 'unpack'" in m and "missing from" in m
        for m in msgs
    )
    # the stale-row leg anchors in the doc, the missing-row leg in code
    paths = {p["path"] for p in problems}
    assert paths == {"docs/DESIGN.md", "registrar_tpu/shard.py"}


def test_bound_check_sanitizes_peer_allocation(tmp_path):
    # The taint-boundary-drift fixture's shard.py is exactly the
    # unbounded-peer-allocation fixture plus a dominating bound check —
    # run it WITHOUT the docs table and the allocation rule must stay
    # silent (the comparison against a constant cleanses the count).
    files = dict(PROGRAM_SEEDED_VIOLATIONS["taint-boundary-drift"])
    del files["docs/DESIGN.md"]
    tree = seed_program_tree(tmp_path, files)
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_epoch_recheck_sanctions_stale_read(tmp_path):
    # The agent's repair idiom: snapshot, await, then consult an epoch
    # field of the SAME receiver in a guard before writing back — the
    # guard load between the await and the write sanctions the write.
    tree = seed_program_tree(tmp_path, {
        "registrar_tpu/agent.py": """\
            import asyncio

            repair_lock = asyncio.Lock()


            async def guarded(ee):
                async with repair_lock:
                    ee.count = 0


            async def bump(ee):
                snap = ee.count
                epoch = ee.epoch
                await asyncio.sleep(0)
                if ee.epoch != epoch:
                    return
                ee.count = snap + 1
            """,
    })
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_reread_after_await_sanctions_stale_read(tmp_path):
    tree = seed_program_tree(tmp_path, {
        "registrar_tpu/agent.py": """\
            import asyncio

            repair_lock = asyncio.Lock()


            async def guarded(ee):
                async with repair_lock:
                    ee.count = 0


            async def bump(ee):
                snap = ee.count
                await asyncio.sleep(0)
                snap = ee.count
                ee.count = snap + 1
            """,
    })
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_lock_block_sanctions_stale_read(tmp_path):
    # Read and write inside ONE `async with lock` block: the lock owns
    # the atomicity (the async-with entry is an await point, but it sits
    # before the read, not between read and write).
    tree = seed_program_tree(tmp_path, {
        "registrar_tpu/agent.py": """\
            import asyncio

            repair_lock = asyncio.Lock()


            async def bump(ee):
                async with repair_lock:
                    snap = ee.count
                    await asyncio.sleep(0)
                    ee.count = snap + 1
            """,
    })
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_reload_base_pinning_shape_stays_silent(tmp_path):
    # The config-reload idiom (agent.py): snapshot `_applied_desired`,
    # take the single-flight lock (an await point), consult receiver
    # fields in guards, write the pin back — sanctioned by the guard
    # loads, never reported.  The Entry class defines the private attr
    # so the foreign-receiver poke is same-module cooperation.
    tree = seed_program_tree(tmp_path, {
        "registrar_tpu/agent.py": """\
            class Entry:
                def __init__(self):
                    self._applied_desired = None
                    self.down = False


            async def reload(ee, lock):
                base = ee._applied_desired
                async with lock:
                    if ee.down:
                        ee._applied_desired = None
                        return "applied"
                    ee._applied_desired = base
                return "noop"
            """,
    })
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_zkcache_gen_counter_shape_stays_silent(tmp_path):
    # The ZKCache generation-counter idiom: the epoch-ish `_gens` dict
    # is read through .get() and written through a subscript — neither
    # is a whole-field snapshot/clobber, so the scan has nothing to say.
    tree = seed_program_tree(tmp_path, {
        "registrar_tpu/zkcache.py": """\
            import asyncio


            class ZKCache:
                def __init__(self):
                    self._gens = {}

                async def lookup(self, path):
                    gen = self._gens.get(path, 0)
                    await asyncio.sleep(0)
                    if self._gens.get(path, 0) != gen:
                        return None
                    return gen
            """,
    })
    proc = run_checker("registrar_tpu", "--no-baseline", cwd=tree)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_taint_stats_in_json_block(tmp_path):
    # --stats must carry the generation-5 phase numbers so the CI
    # summary can echo them.
    tree = seed_program_tree(
        tmp_path, PROGRAM_SEEDED_VIOLATIONS["unbounded-peer-allocation"]
    )
    proc = run_checker(
        "registrar_tpu", "--no-baseline", "--format", "json", "--stats",
        cwd=tree,
    )
    prog = json.loads(proc.stdout)["stats"]["program"]
    for key in (
        "taint_sources", "taint_sinks", "taint_sanitized",
        "taint_build_s", "atomicity_tracked", "atomicity_build_s",
    ):
        assert key in prog, key
    assert prog["taint_sources"] >= 1
    assert prog["taint_sinks"] >= 1


# --- SARIF output ------------------------------------------------------------


def test_sarif_shape_and_chain(tmp_path):
    tree = seed_program_tree(
        tmp_path, PROGRAM_SEEDED_VIOLATIONS["transitive-blocking-call"]
    )
    proc = run_checker(
        "registrar_tpu", "--no-baseline", "--format", "sarif", cwd=tree
    )
    assert proc.returncode == 1
    sarif = json.loads(proc.stdout)
    assert sarif["version"] == "2.1.0"
    assert "sarif-2.1.0" in sarif["$schema"]
    (run,) = sarif["runs"]
    driver = run["tool"]["driver"]
    assert driver["name"] == "checklib"
    rule_ids = {r["id"] for r in driver["rules"]}
    # every registered rule AND the engine findings are declared
    for rule in EXPECTED_RULES + ["syntax-error", "stale-baseline"]:
        assert rule in rule_ids
    (result,) = run["results"]
    assert result["ruleId"] == "transitive-blocking-call"
    assert result["level"] == "error"
    (loc,) = result["locations"]
    phys = loc["physicalLocation"]
    assert phys["artifactLocation"]["uri"] == "registrar_tpu/seeded.py"
    assert phys["region"]["startLine"] >= 1
    # chain evidence maps onto codeFlows/threadFlows hop-for-hop
    (flow,) = result["codeFlows"]
    (thread,) = flow["threadFlows"]
    symbols = [
        h["location"]["message"]["text"] for h in thread["locations"]
    ]
    assert symbols[-1] == "time.sleep"
    assert all(
        h["location"]["physicalLocation"]["region"]["startLine"] >= 1
        for h in thread["locations"]
    )


def test_sarif_clean_tree_has_no_results(tmp_path):
    tree = seed_program_tree(tmp_path, {"registrar_tpu/seeded.py": "x = 1\n"})
    out = tmp_path / "report.sarif"
    proc = run_checker(
        "registrar_tpu", "--no-baseline", "--format", "sarif",
        "--output", str(out), cwd=tree,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    sarif = json.loads(out.read_text())
    assert sarif["runs"][0]["results"] == []


# --- --changed-only / --stats / --max-seconds --------------------------------


def _git(tree, *args):
    return subprocess.run(
        ["git", "-C", str(tree), "-c", "user.email=check@test",
         "-c", "user.name=check", *args],
        capture_output=True,
        text=True,
        check=True,
    )


def seed_changed_only_tree(tmp_path):
    """A scratch git repo with its own copy of tools/ (REPO_ROOT anchors
    there), a helper, a dependent with a file-local violation and a dead
    event, and an unrelated module."""
    import shutil

    shutil.copytree(os.path.join(REPO, "tools"), tmp_path / "tools")
    seed_program_tree(tmp_path, {
        "registrar_tpu/util.py": "def helper():\n    return 1\n",
        "registrar_tpu/consumer.py": """\
            from registrar_tpu.util import helper

            def f(items=[]):
                items.append(helper())
                return items

            def fire(ee):
                ee.emit("registered", 1)
            """,
        "registrar_tpu/unrelated.py": "x = 1\n",
    })
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    return tmp_path


def run_changed_only(tree, *extra):
    # explicit targets compose with --changed-only: they define the
    # coverage universe, the git status narrows within it
    return subprocess.run(
        [sys.executable, os.path.join(str(tree), "tools", "check.py"),
         "registrar_tpu", "--changed-only", "--no-baseline", *extra],
        capture_output=True,
        text=True,
        cwd=str(tree),
    )


def test_changed_only_pulls_in_reverse_dependencies(tmp_path):
    tree = seed_changed_only_tree(tmp_path)
    # touch ONLY the helper: the dependent module imports it, so the
    # reverse-dependency closure must re-lint consumer.py and find its
    # file-local violation (plus the program-wide dead event, which a
    # narrowed run still reports — full model).
    (tree / "registrar_tpu" / "util.py").write_text(
        "def helper():\n    return 2\n"
    )
    proc = run_changed_only(tree)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "[mutable-default]" in proc.stdout
    assert "consumer.py" in proc.stdout


def test_changed_only_skips_unrelated_file_rules(tmp_path):
    tree = seed_changed_only_tree(tmp_path)
    (tree / "registrar_tpu" / "unrelated.py").write_text("x = 2\n")
    proc = run_changed_only(tree)
    # consumer.py was not re-linted (its mutable-default is invisible to
    # this narrowed run) but the whole-program rules still saw the full
    # model: the dead event name fails the gate regardless.
    assert "[mutable-default]" not in proc.stdout
    assert "[dead-event-name]" in proc.stdout
    assert proc.returncode == 1


def test_changed_only_clean_when_nothing_changed(tmp_path):
    tree = seed_changed_only_tree(tmp_path)
    # fix the seeded problems, commit, touch only the unrelated file
    (tree / "registrar_tpu" / "consumer.py").write_text(
        "from registrar_tpu.util import helper\n\n\n"
        "def f():\n    return [helper()]\n"
    )
    _git(tree, "add", "-A")
    _git(tree, "commit", "-qm", "fix")
    (tree / "registrar_tpu" / "unrelated.py").write_text("x = 3\n")
    proc = run_changed_only(tree)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_changed_only_doc_only_diff_is_a_noop(tmp_path):
    # The diff touches no checked file: the run short-circuits before
    # parsing anything — exit 0 and an explicit --stats note, even
    # though a full run WOULD report the seeded violations.
    tree = seed_changed_only_tree(tmp_path)
    (tree / "NOTES.md").write_text("release notes\n")
    proc = run_changed_only(tree, "--stats")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "analysis skipped" in proc.stderr
    assert "mutable-default" not in proc.stdout
    assert "dead-event-name" not in proc.stdout


def test_check_file_exempts_program_rule_suppressions():
    # check_file runs file rules only; a suppression the FULL gate
    # requires (main.py's drain-walk await-in-lock-free-mutator opt-out)
    # must not surface as 'unused — remove it' on the single-file path.
    findings = check.check_file(
        os.path.join(REPO, "registrar_tpu", "main.py"),
        rel_path="registrar_tpu/main.py",
    )
    assert findings == [], [f.render() for f in findings]


def test_changed_only_from_nested_checkout(tmp_path):
    # git prints status paths relative to the repo TOP-LEVEL: when the
    # project lives in a subdirectory of a larger checkout, the subdir
    # prefix must be stripped or the narrowed set goes empty and the
    # gate silently passes on real violations.
    outer = tmp_path
    tree = outer / "vendor" / "project"
    tree.mkdir(parents=True)
    seed_changed_only_tree(tree)
    # re-root git at the OUTER directory (the nested-checkout shape)
    import shutil

    shutil.rmtree(tree / ".git")
    _git(outer, "init", "-q")
    _git(outer, "add", "-A")
    _git(outer, "commit", "-qm", "seed")
    (tree / "registrar_tpu" / "util.py").write_text(
        "def helper():\n    return 2\n"
    )
    proc = run_changed_only(tree)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "[mutable-default]" in proc.stdout


def test_stats_summary_and_json_stats(tmp_path):
    tree = seed_program_tree(tmp_path, {
        "registrar_tpu/seeded.py": "x = 1\n",
    })
    proc = run_checker("registrar_tpu", "--no-baseline", "--stats", cwd=tree)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "check --stats:" in proc.stderr
    assert "modules" in proc.stderr
    # the generation-4 fixpoints report their own phases (ISSUE 15)
    assert "lock graph " in proc.stderr
    assert "lifecycle fixpoint " in proc.stderr
    proc = run_checker(
        "registrar_tpu", "--no-baseline", "--format", "json", cwd=tree
    )
    report = json.loads(proc.stdout)
    stats = report["stats"]
    assert stats["program"]["modules"] == 1
    for key in (
        "lock_sites", "lock_edges", "lock_build_s",
        "lifecycle_tracked", "lifecycle_build_s",
    ):
        assert key in stats["program"], key
    assert "elapsed_s" in stats
    assert set(stats["program_rules_s"]) == set(PROGRAM_SEEDED_VIOLATIONS)
    # the CI digest's per-generation rollup has all four generations
    assert set(stats["rule_generations"]) >= {"1", "2", "3", "4"}


def test_max_seconds_budget_fails_gate(tmp_path):
    tree = seed_program_tree(tmp_path, {
        "registrar_tpu/seeded.py": "x = 1\n",
    })
    proc = run_checker(
        "registrar_tpu", "--no-baseline", "--max-seconds", "0", cwd=tree
    )
    assert proc.returncode == 1
    assert "--max-seconds" in proc.stderr


@pytest.mark.skipif(
    sys.version_info < (3, 10), reason="match statements need 3.10+"
)
def test_match_capture_patterns_bind(tmp_path):
    source = (
        "def f(x):\n"
        "    match x:\n"
        "        case {'k': v, **rest}:\n"
        "            return v, rest\n"
        "        case [head, *tail]:\n"
        "            return head, tail\n"
        "        case other:\n"
        "            return other\n"
    )
    assert messages(source, tmp_path) == []
