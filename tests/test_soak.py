"""Scale/soak tests: many concurrent registrars against one server.

The production deployment is N independent registrar processes (one per
zone) converging on one ZooKeeper ensemble (SURVEY.md §2).  The reference
has no multi-node test story at all; these exercise it.
"""

import asyncio

from registrar_tpu import binderview
from registrar_tpu.registration import register, unregister
from registrar_tpu.testing.server import ZKServer
from registrar_tpu.zk.client import ZKClient

DOMAIN = "soak.prod.us"
PATH = "/us/prod/soak"
N = 25


def _reg():
    return {
        "domain": DOMAIN,
        "type": "load_balancer",
        "service": {
            "type": "service",
            "service": {"srvce": "_http", "proto": "_tcp", "port": 80},
        },
    }


class TestSoak:
    async def test_many_registrars_converge_and_heartbeat(self):
        server = await ZKServer().start()
        clients = []
        try:
            clients = await asyncio.gather(
                *(ZKClient([server.address]).connect() for _ in range(N))
            )
            all_nodes = await asyncio.gather(
                *(
                    register(c, _reg(), admin_ip=f"10.2.{i // 256}.{i % 256}",
                             hostname=f"soak{i}", settle_delay=0.01)
                    for i, c in enumerate(clients)
                )
            )
            # every instance is visible in the Binder view
            res = await binderview.resolve(clients[0], DOMAIN, "A")
            assert len(res.answers) == N
            # all heartbeats succeed concurrently
            await asyncio.gather(
                *(c.heartbeat(nodes) for c, nodes in zip(clients, all_nodes))
            )
            # half the fleet dies; the survivors' records remain
            for c in clients[: N // 2]:
                await c.close()
            res = await binderview.resolve(clients[-1], DOMAIN, "A")
            assert len(res.answers) == N - N // 2
        finally:
            for c in clients:
                if not c.closed:
                    await c.close()
            await server.stop()

    async def test_register_unregister_churn(self):
        server = await ZKServer().start()
        client = await ZKClient([server.address]).connect()
        try:
            for i in range(20):
                nodes = await register(
                    client, _reg(), admin_ip="10.3.0.1",
                    hostname="churn", settle_delay=0,
                )
                assert await client.exists(nodes[0]) is not None
                await unregister(client, nodes)
                assert await client.exists(nodes[0]) is None
        finally:
            await client.close()
            await server.stop()

    async def test_concurrent_same_domain_reregistration_race(self):
        # Two registrars with the SAME hostname racing (e.g. a stale
        # process and its replacement): the pipeline's cleanup stage makes
        # this converge rather than deadlock; last writer owns the node.
        server = await ZKServer().start()
        c1 = await ZKClient([server.address]).connect()
        c2 = await ZKClient([server.address]).connect()
        try:
            r1, r2 = await asyncio.gather(
                register(c1, _reg(), admin_ip="10.4.0.1", hostname="dup",
                         settle_delay=0.02),
                register(c2, _reg(), admin_ip="10.4.0.2", hostname="dup",
                         settle_delay=0.02),
                return_exceptions=True,
            )
            winners = [r for r in (r1, r2) if not isinstance(r, Exception)]
            assert winners, f"both racers failed: {r1!r} / {r2!r}"
            st = await c1.stat(f"{PATH}/dup")
            assert st.ephemeral_owner in (c1.session_id, c2.session_id)
        finally:
            await c1.close()
            await c2.close()
            await server.stop()
