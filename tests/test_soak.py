"""Scale/soak tests: many concurrent registrars against one server.

The production deployment is N independent registrar processes (one per
zone) converging on one ZooKeeper ensemble (SURVEY.md §2).  The reference
has no multi-node test story at all; these exercise it.
"""

import asyncio

from registrar_tpu import binderview
from registrar_tpu.registration import register, unregister
from registrar_tpu.testing.server import ZKServer
from registrar_tpu.zk.client import ZKClient

DOMAIN = "soak.prod.us"
PATH = "/us/prod/soak"
N = 25


def _reg():
    return {
        "domain": DOMAIN,
        "type": "load_balancer",
        "service": {
            "type": "service",
            "service": {"srvce": "_http", "proto": "_tcp", "port": 80},
        },
    }


class TestSoak:
    async def test_many_registrars_converge_and_heartbeat(self):
        server = await ZKServer().start()
        clients = []
        try:
            clients = await asyncio.gather(
                *(ZKClient([server.address]).connect() for _ in range(N))
            )
            all_nodes = await asyncio.gather(
                *(
                    register(c, _reg(), admin_ip=f"10.2.{i // 256}.{i % 256}",
                             hostname=f"soak{i}", settle_delay=0.01)
                    for i, c in enumerate(clients)
                )
            )
            # every instance is visible in the Binder view
            res = await binderview.resolve(clients[0], DOMAIN, "A")
            assert len(res.answers) == N
            # all heartbeats succeed concurrently
            await asyncio.gather(
                *(c.heartbeat(nodes) for c, nodes in zip(clients, all_nodes))
            )
            # half the fleet dies; the survivors' records remain
            for c in clients[: N // 2]:
                await c.close()
            res = await binderview.resolve(clients[-1], DOMAIN, "A")
            assert len(res.answers) == N - N // 2
        finally:
            for c in clients:
                if not c.closed:
                    await c.close()
            await server.stop()

    async def test_register_unregister_churn(self):
        server = await ZKServer().start()
        client = await ZKClient([server.address]).connect()
        try:
            for i in range(20):
                nodes = await register(
                    client, _reg(), admin_ip="10.3.0.1",
                    hostname="churn", settle_delay=0,
                )
                assert await client.exists(nodes[0]) is not None
                await unregister(client, nodes)
                assert await client.exists(nodes[0]) is None
        finally:
            await client.close()
            await server.stop()

    async def test_concurrent_same_domain_reregistration_race(self):
        # Two registrars with the SAME hostname racing (e.g. a stale
        # process and its replacement): the pipeline's cleanup stage makes
        # this converge rather than deadlock; last writer owns the node.
        server = await ZKServer().start()
        c1 = await ZKClient([server.address]).connect()
        c2 = await ZKClient([server.address]).connect()
        try:
            r1, r2 = await asyncio.gather(
                register(c1, _reg(), admin_ip="10.4.0.1", hostname="dup",
                         settle_delay=0.02),
                register(c2, _reg(), admin_ip="10.4.0.2", hostname="dup",
                         settle_delay=0.02),
                return_exceptions=True,
            )
            winners = [r for r in (r1, r2) if not isinstance(r, Exception)]
            assert winners, f"both racers failed: {r1!r} / {r2!r}"
            st = await c1.stat(f"{PATH}/dup")
            assert st.ephemeral_owner in (c1.session_id, c2.session_id)
        finally:
            await c1.close()
            await c2.close()
            await server.stop()


class TestBookkeepingBounds:
    """Leak detectors: after op storms, every per-connection and
    per-client bookkeeping structure must be back to its resting size —
    growth here is how a long-lived daemon's RSS creeps."""

    async def test_client_and_server_state_bounded_after_storm(self):
        server = await ZKServer().start()
        client = await ZKClient([server.address]).connect()
        try:
            paths = [f"/bk{i}" for i in range(200)]
            await asyncio.gather(*(client.create(p, b"x") for p in paths))
            for _ in range(20):
                await client.heartbeat(paths)
                await client.get_many(paths)
            for p in paths:
                await client.unlink(p)

            # client: no pending futures, no corked frames, no armed
            # watches left behind by the storm
            assert not client._pending
            assert client._corked is None
            assert all(not s for s in client._watch_paths.values())
            # server: reply queues drained, watch tables empty, one
            # session, and the tree back to its resting children
            for conn in server._conns:
                assert not conn._outbuf
            assert all(not t for t in server._watches.values())
            root_children = set((await client.get_children("/")))
            assert root_children == {"zookeeper"}
        finally:
            await client.close()
            await server.stop()

    async def test_daemon_rss_flat_under_fast_heartbeats(self, tmp_path):
        # A real daemon process heartbeating 20x faster than production
        # for a few seconds: RSS after warmup must stay flat (gross-leak
        # detector; /proc only, skipped elsewhere).
        import json as _json
        import os
        import subprocess
        import sys

        if not os.path.isdir("/proc"):
            import pytest

            pytest.skip("needs /proc")
        server = await ZKServer().start()
        cfg = tmp_path / "cfg.json"
        cfg.write_text(_json.dumps({
            "registration": {"domain": "rss.soak.us", "type": "host",
                             "heartbeatInterval": 50},
            "adminIp": "10.5.0.1",
            "zookeeper": {"servers": [{"host": server.host,
                                       "port": server.port}],
                          "timeout": 5000},
        }))
        env = dict(os.environ)
        env["PYTHONPATH"] = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )
        proc = subprocess.Popen(
            [sys.executable, "-m", "registrar_tpu", "-f", str(cfg)],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
        )

        def rss_kb():
            with open(f"/proc/{proc.pid}/status") as f:
                for line in f:
                    if line.startswith("VmRSS:"):
                        return int(line.split()[1])
            raise AssertionError("no VmRSS")

        try:
            probe = await ZKClient([server.address]).connect()
            try:
                deadline = asyncio.get_running_loop().time() + 20
                while (await probe.exists("/us/soak/rss")) is None:
                    assert proc.poll() is None, "daemon exited at startup"
                    assert asyncio.get_running_loop().time() < deadline
                    await asyncio.sleep(0.1)
            finally:
                await probe.close()
            await asyncio.sleep(2.0)  # warmup: allocator high-water settles
            start = rss_kb()
            await asyncio.sleep(5.0)  # ~100 heartbeats
            growth = rss_kb() - start
            assert growth < 2048, f"RSS grew {growth} KiB in 5s"
        finally:
            proc.terminate()
            try:
                await asyncio.to_thread(proc.wait, 15)
            except subprocess.TimeoutExpired:
                proc.kill()
                await asyncio.to_thread(proc.wait)
            await server.stop()
