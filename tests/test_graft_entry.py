"""Driver-harness compliance tests for __graft_entry__.py.

The jax-dependent tests are marked ``jax`` and deselected by default
(pyproject addopts): in some environments jax backend initialization can
take minutes (the image's sitecustomize registers an experimental TPU
plugin at interpreter start), and the default suite must stay hermetic
and fast.  Run them with ``make test-jax`` (or ``pytest -m jax``).
Nothing in this module imports jax at collection time; the deadline test
needs no jax at all and runs in the default suite.
"""

import importlib.util

import pytest

_HAVE_JAX = importlib.util.find_spec("jax") is not None


@pytest.mark.jax
def test_entry_jit_compiles():
    jax = pytest.importorskip("jax")
    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (g.BATCH, g.DOUT)


@pytest.mark.jax
@pytest.mark.parametrize("n", [1, 2, 4, 8])
def test_dryrun_multichip(n):
    # jax runs only in the CPU-pinned child; find_spec (not importorskip)
    # keeps the expensive import out of this parent process.
    if not _HAVE_JAX:
        pytest.skip("jax not installed")
    import __graft_entry__ as g

    g.dryrun_multichip(n)


def test_dryrun_deadline_is_enforced(monkeypatch, tmp_path):
    """The dry run must fail loudly, not hang, when the child wedges.

    Needs no jax (the child is a sleeping stub), so it runs in the
    default suite.
    """
    import __graft_entry__ as g

    stub = tmp_path / "wedged_child.py"
    stub.write_text("import time\ntime.sleep(60)\n")
    monkeypatch.setattr(g, "_DRYRUN_DEADLINE_S", 0.5)
    monkeypatch.setattr(g, "_SELF_PATH", str(stub))
    with pytest.raises(RuntimeError, match="deadline"):
        g.dryrun_multichip(2)


def test_dryrun_child_failure_is_reported(monkeypatch, tmp_path):
    """A child that exits without the OK marker raises, not passes."""
    import __graft_entry__ as g

    stub = tmp_path / "broken_child.py"
    stub.write_text("import sys\nprint('boom')\nsys.exit(3)\n")
    monkeypatch.setattr(g, "_SELF_PATH", str(stub))
    with pytest.raises(RuntimeError, match="child failed"):
        g.dryrun_multichip(2)
