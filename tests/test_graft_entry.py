"""Driver-harness compliance tests for __graft_entry__.py.

The conftest pins JAX to the virtual 8-device CPU platform before import.
"""

import jax
import pytest


def test_entry_jit_compiles():
    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (g.BATCH, g.DOUT)


@pytest.mark.parametrize("n", [1, 2, 4, 8])
def test_dryrun_multichip(n):
    import __graft_entry__ as g

    g.dryrun_multichip(n)
