# Build/test/release targets — the analog of the reference's Makefile
# (reference Makefile:36-95: check/test/release/publish via the eng.git
# framework).  No submodules here; everything is stdlib Python.

PYTHON ?= python3
NAME = registrar
RELEASE_TARBALL = $(NAME)-release.tar.gz
RELSTAGEDIR = /tmp/$(NAME)-release

.PHONY: all check check-core test test-jax chaos restart-e2e bench bench-cached bench-sharded overload-quick dns-quick profile slo slo-quick slo-nines release publish clean

all: check test

# Lint gate (the reference's `make check` runs jsl+jsstyle with shipped
# configs, its Makefile:15,18 + tools/jsl.node.conf): byte-compile, the
# in-tree static analysis suite (tools/checklib/ — file-local name/
# asyncio rules PLUS the whole-program pass: import-graph symbol table,
# call graph, event-name and config-key contracts; docs/CHECKS.md),
# and a strict-warnings import smoke.  The --max-seconds budget guards
# against an analysis-cost regression (a quadratic fixpoint would turn
# every build red, loudly, instead of slowly eating CI); the full tree
# runs in a few seconds, 60 is slow-runner headroom.  `check-core` is
# everything EXCEPT the static checker, for callers that already ran
# tools/check.py themselves (CI invokes it once with --format json so
# the report doubles as the gate and the build artifact).
check: check-core
	$(PYTHON) tools/check.py --stats --max-seconds 60

check-core:
	$(PYTHON) -m compileall -q registrar_tpu tests tools bench.py __graft_entry__.py
	$(PYTHON) bench.py --check-baseline
	$(PYTHON) tools/slo.py --check-baseline
	$(PYTHON) -X dev -W error -c "import registrar_tpu, registrar_tpu.main, \
	    registrar_tpu.testing.server, registrar_tpu.testing.netem, \
	    registrar_tpu.config, \
	    registrar_tpu.tools.zkcli, registrar_tpu.binderview, \
	    registrar_tpu.zkcache, registrar_tpu.metrics, registrar_tpu.shard, \
	    registrar_tpu.dnsfront"

# Hermetic suite: jax-marked tests are deselected via pyproject addopts,
# because jax backend init can take minutes in some environments.  (In the
# reference — its Node.js Makefile, lines 66-68 — `make test` runs the
# whole suite unconditionally; ours matches that by keeping every
# always-runnable test in this target.)
test:
	$(PYTHON) -m pytest tests/ -x -q

# Opt-in driver-harness compliance tests.  The recipe scrubs the image's
# TPU-plugin sitecustomize triggers (see __graft_entry__._child_env) so
# the pytest parent can never wedge on experimental-backend init.
test-jax:
	env -u PALLAS_AXON_POOL_IPS -u PYTHONPATH JAX_PLATFORMS=cpu \
	    $(PYTHON) -m pytest tests/test_graft_entry.py -m jax -x -q

# Long-form chaos soak: the per-toxic netem armor suite, then a 30 s
# fault-injection storm with network faults routed through ChaosProxy
# (the suite's default run is ~5 s).  CHAOS_SEED=<n> pins a schedule for
# reproduction; CHAOS_NETEM=0 drops back to server-side faults only.
chaos:
	CHAOS_SECONDS=30 $(PYTHON) -m pytest tests/test_netem.py tests/test_chaos.py -x -q

# Ensemble leg (ISSUE 10): a seeded 3-member quorum ensemble under a
# leader-kill + rolling-restart + partition storm with read-only-capable
# workers, plus the ensemble e2e suite (leader death mid-registration,
# quorum loss, rolling restart under a polling resolver).  Same
# CHAOS_SEED knob as `chaos`.
chaos-ensemble:
	CHAOS_SECONDS=20 $(PYTHON) -m pytest \
	    "tests/test_chaos.py::test_chaos_ensemble_quorum_storm" \
	    tests/test_ensemble.py -x -q

# Zero-downtime restart e2e (ISSUE 5): the real daemon is SIGTERMed and
# relaunched mid-resolve-loop — handoff mode must show ZERO NO_NODE
# observations (same ZK session resumed across the process boundary),
# drain mode a bounded re-registration gap; every degraded statefile
# shape must land in a clean fresh-session registration.  Wired into
# the CI chaos job.
restart-e2e:
	$(PYTHON) -m pytest tests/test_restart_e2e.py -x -q

bench:
	$(PYTHON) bench.py

# Profile the two perf-round hot loops (warm cached resolve; 1000-znode
# heartbeat sweep, solo + coalesced) under cProfile and write the top-25
# cumulative report to profile-report.txt — so the next perf round
# starts from data, not guesses (ISSUE 11).  CI's bench smoke leg
# uploads the report as an artifact on every PR.
profile:
	$(PYTHON) bench.py --profile

# Availability-SLO simulator (ISSUE 9): a seeded fleet of in-process
# registrars under named churn traces (every docs/FAULTS.md fault
# class) while a resolver polls continuously; emits slo-report.json
# (nines, per-fault MTTD/MTTR, worst outage + trace ids) and gates the
# quick trace against SLO_BASELINE.json like the perf benches.
# slo-quick additionally reruns the same seed with repair disabled and
# fails unless the nines measurably drop (the detection proof).  Both
# targets also write the worst outage's ASSEMBLED cross-process trace
# tree (ISSUE 13) next to the report: slo-report.worst-trace.{json,txt}
# — probe span -> router relay -> worker resolve subtree, one trace id.
# SLO_SEED=<n> pins a schedule; SLO_TOLERANCE_PCT widens the gate on
# slow hardware; SLO_GATE=0 disables it.
slo:
	$(PYTHON) tools/slo.py --trace full --report slo-report.json

slo-quick:
	$(PYTHON) tools/slo.py --trace quick --report slo-report.json --prove-detection

# Lever proof (ISSUE 20): run the quick trace twice under ONE seed —
# availability levers on (the default), then the reference-exact tuning
# (--reference) — and fail unless the levers measurably beat the
# reference nines.  The per-fault table attributes the gain.
slo-nines:
	$(PYTHON) tools/slo.py --trace quick --report slo-report.json --prove-levers

# Cached-resolve slice (ISSUE 4): the zkcache coherence suite, then the
# cached-latency/QPS/coherence-lag measurement with its in-process >=10x
# check.  Run by the CI chaos job so the coherence-lag path is exercised
# on every change, independent of the cross-round gate.
bench-cached:
	$(PYTHON) -m pytest tests/test_zkcache.py -x -q
	$(PYTHON) bench.py --cached-only

# Sharded serve tier slice (ISSUE 12): the shard suite (ring stability,
# parity, resharding, crash supervision), then the scaling matrix +
# warm-handoff measurement with its in-process zero-error assert (and,
# on >=4 cores, the >=3x 4-vs-1 scaling bound).  The CI bench smoke leg
# runs this under BENCH_SMOKE=1 (reduced scale) because the gated bench
# run reports the sharded metrics as null there — multi-process scaling
# on a shared CI core gates nothing real.
bench-sharded:
	$(PYTHON) -m pytest tests/test_shard.py -x -q
	$(PYTHON) bench.py --sharded-only

# Overload-armor slice (ISSUE 17): the admission/shedding suite, then a
# seeded heavy-tailed storm (Zipf popularity + flash crowd + never-exists
# churn + malformed frames + slow-loris/half-open clients) paced at ~5x
# measured capacity against an ARMORED 2-shard tier.  Hard-fails on any
# admitted-request timeout (sheds must fail FAST, never look like
# timeouts) or on a storm that sheds nothing (no overload reached = the
# measurement is vacuous).  The storm seed is printed in a replay line —
# BENCH_OVERLOAD_SEED=<seed> pins it — and echoed into the CI chaos
# job's summary.  BENCH_SMOKE=1 drops to reduced scale for shared cores.
overload-quick:
	$(PYTHON) -m pytest tests/test_overload.py -x -q
	$(PYTHON) bench.py --overload-only

# DNS frontend slice (ISSUE 19): the golden wire suite (codec vectors,
# truncation->TCP retry, NXDOMAIN/NODATA negatives, watch-coherent
# encode cache incl. RFC 8767 serve-stale), then a seeded Zipf query
# storm over real UDP sockets against a 4-shard SO_REUSEPORT tier —
# asserting the >0.9 encode-cache hit ratio and (non-smoke) warm DNS
# QPS within 25% of the unix-socket sharded path.  The storm seed is
# printed in a replay line — BENCH_DNS_SEED=<seed> pins it — and echoed
# into the CI chaos job's summary.  BENCH_SMOKE=1 drops to reduced
# scale for shared cores.
dns-quick:
	$(PYTHON) -m pytest tests/test_dns_golden.py -x -q
	$(PYTHON) bench.py --dns-only

# Release tarball rooted at $(PREFIX) (the reference roots its tarball
# at /opt/smartdc/registrar, Makefile:70-95).  The SMF manifest is
# generated from its .xml.in template at build time, like the
# reference's SMF_MANIFESTS_IN substitution (reference Makefile:19):
# the shipped registrar.xml is svccfg-importable as-is, no @@ tokens.
PREFIX ?= /opt/registrar
# Top-level path component of $(PREFIX) — what the tarball is rooted at
# (so a non-/opt PREFIX still builds).
PREFIX_TOP = $(firstword $(subst /, ,$(PREFIX)))
release:
	rm -rf $(RELSTAGEDIR)
	mkdir -p $(RELSTAGEDIR)$(PREFIX)/etc $(RELSTAGEDIR)$(PREFIX)/smf/manifests
	cp -r registrar_tpu systemd docs $(RELSTAGEDIR)$(PREFIX)/
	sed 's|@@PREFIX@@|$(PREFIX)|g' smf/manifests/registrar.xml.in \
	    > $(RELSTAGEDIR)$(PREFIX)/smf/manifests/registrar.xml
	cp etc/config.coal.json etc/config.example.json $(RELSTAGEDIR)$(PREFIX)/etc/
	cp README.md LICENSE pyproject.toml $(RELSTAGEDIR)$(PREFIX)/
	find $(RELSTAGEDIR) -name __pycache__ -type d | xargs rm -rf
	tar -czf $(RELEASE_TARBALL) -C $(RELSTAGEDIR) $(PREFIX_TOP)
	rm -rf $(RELSTAGEDIR)
	@echo "release: $(RELEASE_TARBALL)"

# Parity with the reference's `make publish` (Makefile:70-95 uploads the
# tarball to a bits directory); here: copy to $(PUBLISH_DIR).
PUBLISH_DIR ?= /tmp/registrar-bits
publish: release
	mkdir -p $(PUBLISH_DIR)
	cp $(RELEASE_TARBALL) $(PUBLISH_DIR)/
	@echo "published: $(PUBLISH_DIR)/$(RELEASE_TARBALL)"

clean:
	rm -f $(RELEASE_TARBALL)
	find . -name __pycache__ -type d | xargs rm -rf
